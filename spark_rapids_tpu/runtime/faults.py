"""Unified fault injection + runtime recovery bookkeeping.

Reference: the resilience machinery is scattered in the reference —
``RmmSpark.forceRetryOOM`` injects OOM per thread (SURVEY §2.5),
``RapidsShuffleHeartbeatManager`` evicts dead peers (§2.6), and
``onTaskFailed`` handles fatal errors — but each fault class has its own
ad-hoc test hook. This module unifies them: one conf-driven registry of
NAMED fault points (``spark.rapids.test.faults``) threaded through
dispatch, exec execute paths, the shuffle client/server/transport and the
io readers/writers, each armed with a deterministic seeded schedule and a
per-point fire counter, plus the recovery-side state the engine consults:

* ``FAULTS`` — the process-wide :class:`FaultRegistry`; sites call
  :func:`fault_point` (the greppable marker the RL-FAULT-POINT lint rule
  audits against :data:`FAULT_POINTS`).
* ``RECOVERY`` — counters for every recovery action (fetch retries, peer
  exclusions, map recomputes, circuit-breaker demotions, query replays)
  so chaos runs can assert bounded retry counts.
* ``CIRCUIT_BREAKER`` — per-operator non-OOM failure counts; after
  ``spark.rapids.sql.runtimeFallback.maxFailures`` failures of the same
  op it is demoted to the CPU fallback path for the rest of the session
  (surfaced as a fallback reason through PlanMeta/explain).
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

from spark_rapids_tpu.errors import (
    ColumnarProcessingError,
    KernelCrashError,
    RetryOOM,
    ShuffleFetchError,
    ShuffleTransportError,
)
from spark_rapids_tpu.lockorder import ordered_lock

#: injectable fault kinds and the failure each simulates
FAULT_KINDS = (
    "oom",         # device allocation failure (RetryOOM; the retry framework survives it)
    "crash",       # non-OOM kernel failure (KernelCrashError; circuit breaker territory)
    "fetch",       # shuffle block fetch failure (ShuffleFetchError; fetch-retry loop)
    "disconnect",  # transport connection drop (ShuffleTransportError; reconnect + retry)
    "corrupt",     # bit-flip a data frame (CRC catches it; refetch recovers)
    "slow",        # slow peer / stall (sleep; exercises timeouts without failing)
    "wedge",       # long stall INSIDE one dispatch (no exception; the cooperative
                   # cancel boundary never runs — watchdog hard-timeout territory)
    "device_lost", # fatal device/tunnel loss (DeviceLostError; health-monitor
                   # recovery: backend reinit + cache invalidation, NOT the breaker)
    "race",        # lost optimistic-concurrency race (DeltaConcurrentModification-
                   # Exception; the transaction's rebase-and-retry loop owns it)
)

#: registered fault points: name -> (module that hosts the call site, doc).
#: The RL-FAULT-POINT repo-lint rule asserts every entry here names an
#: existing ``fault_point("<name>")`` call in that module and that no call
#: site uses an unregistered name.
FAULT_POINTS: Dict[str, tuple] = {
    "dispatch.kernel": (
        "spark_rapids_tpu/dispatch.py",
        "before each jitted kernel dispatch"),
    "stream.batch": (
        "spark_rapids_tpu/streaming/query.py",
        "after a micro-batch's offsets are durably logged, before it "
        "executes (a crash here leaves a pending batch; resume re-runs "
        "the SAME offsets)"),
    "stream.sink.commit": (
        "spark_rapids_tpu/streaming/sink.py",
        "after the sink's replay check, before the transactional "
        "commit (a crash here re-runs the batch; the txn watermark "
        "dedupes the replay)"),
    "exec.execute": (
        "spark_rapids_tpu/runtime/faults.py",
        "at each device exec's execute()/execute_masked() boundary "
        "(installed by install_fault_boundaries; carries op context)"),
    "shuffle.fetch.metadata": (
        "spark_rapids_tpu/shuffle/client_server.py",
        "client metadata round trip"),
    "shuffle.fetch.stream": (
        "spark_rapids_tpu/shuffle/client_server.py",
        "client block reassembly (corrupt applies to completed blocks)"),
    "shuffle.transport.request": (
        "spark_rapids_tpu/shuffle/transport.py",
        "transport request channel"),
    "shuffle.transport.stream": (
        "spark_rapids_tpu/shuffle/transport.py",
        "transport data-window stream (corrupt flips window bytes)"),
    "shuffle.read.partition": (
        "spark_rapids_tpu/shuffle/manager.py",
        "multithreaded manager per-map segment read"),
    "shuffle.write.map": (
        "spark_rapids_tpu/shuffle/manager.py",
        "multithreaded manager map-output write"),
    "io.read.file": (
        "spark_rapids_tpu/io/common.py",
        "file-source per-file decode"),
    "io.write.file": (
        "spark_rapids_tpu/io/writer.py",
        "writer per-file write (BOTH branches: single-file part-00000 "
        "and every dynamic-partition file), before the staged write"),
    "io.write.commit": (
        "spark_rapids_tpu/io/committer.py",
        "task commit, before each staged file's atomic promotion "
        "(os.replace into the final destination)"),
    "io.write.abort": (
        "spark_rapids_tpu/io/committer.py",
        "write-job abort, before the rollback + staging sweep (a crash "
        "here exercises the crash-handler/atexit sweep backstop)"),
    "delta.commit.race": (
        "spark_rapids_tpu/delta/log.py",
        "immediately before the atomic commit-file create; kind "
        "'race' injects a DeltaConcurrentModificationException so the "
        "optimistic rebase-and-retry loop is exercisable without a "
        "real concurrent writer, 'crash' dies mid-commit"),
    "service.worker_crash": (
        "spark_rapids_tpu/service/scheduler.py",
        "service worker runner, after the RUNNING transition and "
        "before the query executes — an exception here kills the "
        "WORKER (not the query), exercising respawn + requeue"),
    "device.lost": (
        "spark_rapids_tpu/dispatch.py",
        "before each jitted kernel dispatch; device_lost simulates a "
        "fatal PJRT/tunnel loss (health-monitor recovery path)"),
    "kernels.sort": (
        "spark_rapids_tpu/kernels/sort.py",
        "at the Pallas multi-column sort's trace-time entry; a crash "
        "here demotes the 'sort' primitive to the HLO lax.sort path"),
    "kernels.segreduce": (
        "spark_rapids_tpu/kernels/segreduce.py",
        "at the Pallas segmented-reduction entries (fused two-limb "
        "min/max, one-hot split-sum partials); a crash demotes "
        "'segreduce' to the HLO scatter/einsum paths"),
    "kernels.hashprobe": (
        "spark_rapids_tpu/kernels/hashprobe.py",
        "at the Pallas hash-probe entry; a crash demotes 'hashprobe' "
        "to the sort-based dense-rank probe"),
    "kernels.compact": (
        "spark_rapids_tpu/kernels/compact.py",
        "at the Pallas row-compaction entry; a crash demotes "
        "'compact' to the per-column scatter_pair path"),
    "dispatch.wedge": (
        "spark_rapids_tpu/dispatch.py",
        "before each jitted kernel dispatch; wedge stalls INSIDE the "
        "dispatch so only the watchdog's hard wall limit can end it"),
    # -- the mesh fault domain: every stage of the distributed path is
    # injectable, and ``device_lost`` at any ``mesh.*`` point raises the
    # PARTIAL MeshDeviceLostError (one mesh device dead, backend alive)
    # that walks the degradation ladder instead of the whole-backend
    # reinit (runtime/health.py on_mesh_device_loss)
    "mesh.shard.put": (
        "spark_rapids_tpu/parallel/mesh.py",
        "per-shard device landing (jax.device_put under the row "
        "sharding): every mesh-native scan upload and exchange reshard "
        "passes through here, before the transfer"),
    "mesh.ici.exchange": (
        "spark_rapids_tpu/parallel/exchange.py",
        "the ICI all-to-all: a data-less site before the collective "
        "dispatch (crash/device_lost/slow) plus the checksummed "
        "per-partition live-count fetch (corrupt flips the fetched "
        "bytes; the TPAK-v2 digest riding the same fetch catches the "
        "damage and the intact device value is refetched)"),
    "mesh.gather": (
        "spark_rapids_tpu/execs/mesh.py",
        "the MeshReland device-to-device gather (DeviceTable."
        "unsharded): corrupt damages the LANDED copy (sentinel-driven "
        "device bit-flip) and the row-count+checksum validation trips, "
        "re-landing from the still-sharded source instead of feeding a "
        "wide kernel silently wrong shards"),
    "mesh.dict.upload": (
        "spark_rapids_tpu/parallel/exchange.py",
        "replicated string-dictionary upload (interned_dict_bytes), "
        "before the device_put replication across the mesh"),
    # -- the HOST fault domain: every stage of the multi-host
    # driver/executor protocol is injectable, and ``device_lost`` at any
    # ``host.*`` point raises the typed HostLostError (a whole executor
    # PROCESS died, not a device) that walks the HOST degradation
    # ladder (runtime/health.py on_host_loss) instead of the mesh
    # ladder or a whole-backend reinit
    "host.dispatch": (
        "spark_rapids_tpu/runtime/cluster.py",
        "driver->executor scan dispatch, before the request round "
        "trip (ClusterDriver.scan_host): crash exercises the query-"
        "replay path, device_lost the host degradation ladder"),
    "host.shard.land": (
        "spark_rapids_tpu/runtime/cluster.py",
        "per host-shard landing of an executor's scan response "
        "(ClusterDriver.scan): corrupt damages the landed TPAK frame "
        "and the CRC catches it — the intact received frame re-lands "
        "(hostShardRetries) instead of feeding a scan garbage rows"),
    "host.dcn.exchange": (
        "spark_rapids_tpu/runtime/cluster.py",
        "before a shuffle collective whose mesh spans more than one "
        "cluster host group (the all-to-all crosses the DCN axis; "
        "dcn_exchange_point, called by the ICI exchange)"),
    "host.heartbeat": (
        "spark_rapids_tpu/runtime/cluster.py",
        "executor heartbeat receipt at the driver's ledger: an "
        "injected fault DROPS the beat (counted) — enough dropped "
        "beats and the missed-beat sweep declares the host lost, the "
        "exact path a wedged executor takes"),
    # -- the MEMORY fault domain: out-of-core execution under the hard
    # device budget (runtime/memory.py MemoryArbiter) is injectable at
    # every stage of the reserve->spill->unspill cycle
    "mem.reserve": (
        "spark_rapids_tpu/runtime/memory.py",
        "before the arbiter grants a device-landing reservation: 'oom' "
        "simulates a budget squeeze mid-query (RetryOOM into the "
        "retry framework: spill-replay, split-and-retry, then the "
        "memory degradation ladder)"),
    "mem.spill": (
        "spark_rapids_tpu/runtime/spill.py",
        "before a device->host spill demotion: 'crash' simulates a "
        "spill FAILURE (the demotion path itself dies — circuit-"
        "breaker/replay territory, the buffer stays device-resident)"),
    "mem.unspill": (
        "spark_rapids_tpu/runtime/spill.py",
        "at the disk-tier unspill read: 'corrupt' flips frame bytes "
        "and the TPAK-convention CRC footer catches it — typed "
        "SpillCorruptionError re-lands from the scan cache via query "
        "replay instead of serving wrong bytes"),
}

_SLOW_SLEEP_S = 0.05
#: how long a ``wedge`` fault stalls inside one dispatch — longer than
#: any sane spark.rapids.service.hardTimeoutMs test setting, short
#: enough that a seeded chaos run still terminates promptly
_WEDGE_SLEEP_S = 2.0


class _ArmedFault:
    """One armed '<point>[@<op>]:<kind>:<prob-or-count>[:<seed>]' entry."""

    __slots__ = ("point", "op", "kind", "prob", "remaining", "rng", "fired")

    def __init__(self, point: str, op: Optional[str], kind: str,
                 prob: Optional[float], count: Optional[int], seed: int):
        self.point = point
        self.op = op
        self.kind = kind
        self.prob = prob
        self.remaining = count
        self.rng = random.Random(seed)
        self.fired = 0

    def should_fire(self) -> bool:
        if self.remaining is not None:
            if self.remaining <= 0:
                return False
            self.remaining -= 1
            return True
        return self.rng.random() < (self.prob or 0.0)


def parse_fault_spec(spec: str) -> List[_ArmedFault]:
    """Parse the ``spark.rapids.test.faults`` value. Raises on unknown
    points/kinds so a typo'd chaos schedule fails loudly, not silently."""
    out: List[_ArmedFault] = []
    for i, entry in enumerate(e.strip() for e in spec.split(";")):
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ColumnarProcessingError(
                f"bad fault spec entry {entry!r} (want "
                "<point>[@<op>]:<kind>:<prob-or-count>[:<seed>])")
        target, kind, amount = parts[0], parts[1].lower(), parts[2]
        point, _, op = target.partition("@")
        if point not in FAULT_POINTS:
            raise ColumnarProcessingError(
                f"unknown fault point {point!r} (known: "
                f"{', '.join(sorted(FAULT_POINTS))})")
        if kind not in FAULT_KINDS:
            raise ColumnarProcessingError(
                f"unknown fault kind {kind!r} (known: "
                f"{', '.join(FAULT_KINDS)})")
        prob = count = None
        if "." in amount:
            prob = float(amount)
            if not 0.0 < prob <= 1.0:
                raise ColumnarProcessingError(
                    f"fault probability {prob} outside (0, 1]")
        else:
            count = int(amount)
            if count < 1:
                raise ColumnarProcessingError(
                    f"fault count {count} must be >= 1")
        seed = int(parts[3]) if len(parts) == 4 else i
        out.append(_ArmedFault(point, op or None, kind, prob, count, seed))
    return out


class FaultRegistry:
    """Process-wide armed faults + per-point fire counters."""

    def __init__(self):
        self._lock = ordered_lock("faults.registry")
        self._armed: List[_ArmedFault] = []
        self._spec = ""
        self._counters: Dict[str, int] = {}

    def arm(self, spec: str) -> None:
        """(Re-)arm from a spec string. Re-arming the SAME spec is a no-op
        so per-query execute() calls don't reset seeded schedules or
        counters mid-session; a different spec replaces everything."""
        with self._lock:
            if spec == self._spec:
                return
            self._spec = spec
            self._armed = parse_fault_spec(spec) if spec else []
            self._counters = {}

    def disarm(self) -> None:
        with self._lock:
            self._spec = ""
            self._armed = []
            self._counters = {}

    @contextmanager
    def suspended(self):
        """Temporarily disarm WITHOUT losing the armed entries' RNG
        state or counters — for a fault-free interlude (e.g. the chaos
        harness re-collecting a baseline) inside a seeded run whose
        schedule must keep advancing, not reset."""
        with self._lock:
            saved = (self._spec, self._armed, self._counters)
            self._spec, self._armed, self._counters = "", [], {}
        try:
            yield
        finally:
            with self._lock:
                self._spec, self._armed, self._counters = saved

    @property
    def armed(self) -> bool:
        return bool(self._armed)

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def fire(self, point: str, op: Optional[str] = None, data=None):
        """Evaluate every armed entry matching ``point`` (and ``op`` when
        the entry carries an @op filter). Raises the matched kind's
        exception; ``corrupt`` instead returns a damaged copy of
        ``data``; ``slow`` sleeps. Returns ``data`` (possibly corrupted)
        so corruption-capable sites can write ``data = fault_point(...,
        data=data)``."""
        if not self._armed:
            return data
        with self._lock:
            hits = [a for a in self._armed
                    if a.point == point
                    and (a.op is None or a.op == op)
                    # corruption needs bytes to corrupt: a data-less call
                    # at the same point must not consume the schedule
                    and (a.kind != "corrupt" or data is not None)
                    and a.should_fire()]
            for a in hits:
                a.fired += 1
                key = a.point if a.op is None else f"{a.point}@{a.op}"
                self._counters[key] = self._counters.get(key, 0) + 1
        for a in hits:
            where = point if op is None else f"{point}[{op}]"
            if a.kind == "oom":
                raise RetryOOM(f"injected device OOM at {where}")
            if a.kind == "crash":
                # no fault_op here: attribution is the exec fault guards'
                # job (_tag_fault_op), so the breaker only ever counts
                # PLAN-NODE names — a crash injected at a helper exec or
                # kernel propagates to the nearest rule-rooted ancestor
                raise KernelCrashError(f"injected kernel crash at {where}")
            if a.kind == "fetch":
                raise ShuffleFetchError(f"injected fetch error at {where}")
            if a.kind == "disconnect":
                raise ShuffleTransportError(
                    f"injected transport disconnect at {where}")
            if a.kind == "device_lost":
                if point.startswith("host."):
                    # a whole executor PROCESS died (the backend and
                    # its devices are fine) — the HOST degradation
                    # ladder (runtime/health.py on_host_loss) owns
                    # recovery
                    from spark_rapids_tpu.errors import HostLostError
                    raise HostLostError(
                        f"injected host loss at {where}")
                if point.startswith("mesh."):
                    # PARTIAL loss: one mesh device died, the backend
                    # is otherwise alive — the degradation ladder
                    # (runtime/health.py) owns recovery, not the
                    # whole-backend reinit
                    from spark_rapids_tpu.errors import MeshDeviceLostError
                    raise MeshDeviceLostError(
                        f"injected mesh device loss at {where}")
                from spark_rapids_tpu.errors import DeviceLostError
                raise DeviceLostError(
                    f"injected device loss at {where}")
            if a.kind == "race":
                from spark_rapids_tpu.delta.log import (
                    DeltaConcurrentModificationException,
                )
                raise DeltaConcurrentModificationException(
                    f"injected optimistic-concurrency race at {where}")
            if a.kind == "wedge":
                import os
                time.sleep(float(os.environ.get("SRT_WEDGE_SLEEP_S",
                                                _WEDGE_SLEEP_S)))
            elif a.kind == "slow":
                time.sleep(_SLOW_SLEEP_S)
            elif a.kind == "corrupt" and data is not None and len(data):
                buf = bytearray(data)
                pos = a.rng.randrange(len(buf))
                buf[pos] ^= 0xFF
                data = bytes(buf)
        return data


FAULTS = FaultRegistry()


def fault_point(name: str, op: Optional[str] = None, data=None):
    """THE site marker for injectable faults. Every call names a point
    registered in :data:`FAULT_POINTS` (the RL-FAULT-POINT lint rule
    audits both directions). Disarmed cost is one attribute read."""
    if not FAULTS._armed:
        return data
    return FAULTS.fire(name, op=op, data=data)


# ---------------------------------------------------------------------------
# Recovery accounting
# ---------------------------------------------------------------------------


class RecoveryStats:
    """Process-wide counters for every recovery action the engine takes;
    chaos runs snapshot/diff these to report and bound recovery work.
    Backed by the unified metric registry's ``recovery`` scope
    (obs/metrics.py) so the event log reads the same numbers."""

    FIELDS = ("fetch_retries", "peer_exclusions", "recomputed_maps",
              "demotions", "query_replays")

    def __init__(self):
        from spark_rapids_tpu.obs.metrics import (
            metric_scope,
            register_metric,
        )
        self._lock = ordered_lock("faults.recovery")
        self._counts = metric_scope("recovery")
        for f in self.FIELDS:
            register_metric(f, "count", "ESSENTIAL",
                            f"recovery action counter ({f})")
            self._counts.setdefault(f, 0)

    def bump(self, field: str, n: int = 1) -> None:
        if field not in self._counts:
            raise KeyError(field)  # typo'd field, fail loud
        with self._lock:
            self._counts.add(field, n)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def reset(self) -> None:
        with self._lock:
            for f in self.FIELDS:
                self._counts[f] = 0


RECOVERY = RecoveryStats()


def backoff_retry(fn, *, max_retries: int, wait_s: float,
                  backoff_mult: float, retryable, on_failure=None):
    """THE exponential-backoff retry loop both shuffle read paths share
    (p2p peer fetches and the multithreaded manager's file reads —
    one policy, one accounting site). Each failure bumps
    RECOVERY.fetch_retries and calls ``on_failure(exc, attempt)``; a
    truthy return stops retrying immediately (e.g. a chronic-flakiness
    budget). On exhaustion the LAST exception re-raises — callers wrap
    it in MapOutputLostError with their own context."""
    attempt = 0
    wait = wait_s
    while True:
        try:
            return fn()
        except retryable as e:
            attempt += 1
            RECOVERY.bump("fetch_retries")
            stop = on_failure(e, attempt) if on_failure is not None else False
            if stop or attempt > max_retries:
                raise
            time.sleep(wait)
            wait *= backoff_mult


# ---------------------------------------------------------------------------
# Per-operator circuit breaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """After N repeated non-OOM device failures of the same operator, the
    op is demoted to the CPU fallback path — PROCESS-WIDE, like the
    speculation blocklist: a kernel that crashes the shared device is
    broken for every session in this engine process, so all of them see
    the demotion until reset(). Keys are PLAN-NODE class names (the unit
    the overrides layer falls back at); the demotion reason feeds
    PlanMeta.reasons so explain() and the plan verifier's
    fallback-hygiene rule surface it."""

    def __init__(self):
        self._lock = ordered_lock("faults.breaker")
        self._failures: Dict[str, int] = {}
        self._reasons: Dict[str, str] = {}

    def record_failure(self, op: str, exc: BaseException,
                       max_failures: int) -> bool:
        """Count one failure of ``op``; returns True when this failure
        crossed the threshold and demoted the op."""
        first_line = str(exc).splitlines()[0] if str(exc) else type(exc).__name__
        with self._lock:
            if op in self._reasons:
                return False
            n = self._failures.get(op, 0) + 1
            self._failures[op] = n
            if n < max_failures:
                return False
            self._reasons[op] = (
                f"runtime circuit breaker: demoted to CPU after {n} device "
                f"failures (last: {type(exc).__name__}: {first_line})")
        RECOVERY.bump("demotions")
        return True

    def demotion_reason(self, op: str) -> Optional[str]:
        with self._lock:
            return self._reasons.get(op)

    def demoted_ops(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._reasons)

    def reset(self) -> None:
        with self._lock:
            self._failures = {}
            self._reasons = {}


CIRCUIT_BREAKER = CircuitBreaker()


# ---------------------------------------------------------------------------
# Exec fault boundaries (op attribution for crashes + the exec.execute
# injection point)
# ---------------------------------------------------------------------------


def _tag_fault_op(exc: BaseException, op: str) -> None:
    """Attach op attribution to a demotable failure. Innermost exec wins
    (the first wrapper the exception crosses sets it); RETRYABLE OOMs
    are excluded — the retry framework owns those. A FatalDeviceOOM
    (retries + splits exhausted) IS tagged: the memory degradation
    ladder's last rung demotes exactly that operator to the CPU path."""
    from spark_rapids_tpu.errors import FatalDeviceOOM
    from spark_rapids_tpu.runtime.crash_handler import is_fatal_device_error
    from spark_rapids_tpu.runtime.retry import is_device_oom
    if getattr(exc, "fault_op", None) is not None:
        return
    if is_device_oom(exc):
        return
    if (isinstance(exc, (KernelCrashError, FatalDeviceOOM))
            or is_fatal_device_error(exc)):
        exc.fault_op = op


def _guard(fn, op: str, tag: bool):
    def wrapped(*args, **kwargs):
        try:
            # inside the try: an injected crash at THIS exec's own
            # boundary gets tagged by this wrapper (the root exec has no
            # ancestor wrapper to do it)
            fault_point("exec.execute", op=op)
            for batch in fn(*args, **kwargs):
                yield batch
        except Exception as exc:
            if tag:
                _tag_fault_op(exc, op)
            raise
    return wrapped


def install_fault_boundaries(executable) -> None:
    """Wrap every device exec's execute()/execute_masked() in the
    converted tree with (a) the ``exec.execute`` fault point and (b)
    op attribution for non-OOM device failures, feeding the circuit
    breaker. Idempotent per exec instance (plans are re-executed)."""
    from spark_rapids_tpu.execs.base import TpuExec
    from spark_rapids_tpu.lore import _iter_tree
    for e in _iter_tree(executable):
        if not isinstance(e, TpuExec) or getattr(e, "_fault_guarded", False):
            continue
        e._fault_guarded = True
        # attribution unit: the PLAN-NODE class this exec was converted
        # from (set by overrides/rules._convert — the granularity the
        # overrides layer can fall back at). Helper execs a convert
        # function builds (coalesce wrappers etc.) carry no origin: they
        # fire the injection point under their own class name but leave
        # tagging to the nearest rule-rooted ancestor the exception
        # crosses, so the breaker only ever counts demotable names.
        origin = getattr(e, "_plan_origin", None)
        op = origin or type(e).__name__
        e.execute = _guard(e.execute, op, tag=origin is not None)
        e.execute_masked = _guard(e.execute_masked, op,
                                  tag=origin is not None)
