"""UDF compiler: Python lambdas/functions -> engine expression trees.

Reference: udf-compiler/CatalystExpressionBuilder.scala (5,809 LoC) —
spark-rapids decompiles JVM bytecode of simple Scala/Java UDFs into
Catalyst expressions so they run on the GPU instead of row-at-a-time in
the executor. The Python-native analog inspects the function's SOURCE AST
(Python keeps it, unlike the JVM) and translates the supported subset into
this engine's expressions, so the "UDF" compiles into the same fused XLA
kernels as built-ins:

  arithmetic  + - * / % **        (% maps to Pmod: Python's sign rule)
  comparisons == != < <= > >=     (chained comparisons fold with AND)
  boolean     and or not
  conditional x if c else y
  builtins    abs len round
  str methods .upper .lower .strip .startswith .endswith

Anything else (loops, closures over mutable state, unsupported calls)
falls back to a row-wise CPU ``PythonUDF`` with a RuntimeWarning — same
contract as the reference: compiled when possible, never silently wrong.

Null semantics note (documented divergence from running the Python row by
row): compiled UDFs follow the SQL three-valued semantics of the
translated expressions — arithmetic/comparisons null-propagate, a null
``if`` condition selects the else branch — instead of passing None into
Python code; constructs whose SQL translation would silently diverge
(min/max vs null-skipping Least/Greatest) are rejected to the fallback.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
import warnings
from typing import Callable, Optional, Sequence

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.expr import Expression, Literal, lit


class UdfCompileError(Exception):
    pass


class PythonUDF(Expression):
    """Row-wise CPU fallback (reference: the un-compiled UDF path)."""

    def __init__(self, fn: Callable, return_type: T.DataType,
                 children: Sequence[Expression], name: str = ""):
        self.fn = fn
        self._return_type = return_type
        self.children = tuple(children)
        self._name = name or getattr(fn, "__name__", "udf")

    @property
    def data_type(self):
        return self._return_type

    def key(self):
        return ("pythonudf", id(self.fn), str(self._return_type),
                tuple(c.key() for c in self.children))

    def with_children(self, children):
        return PythonUDF(self.fn, self._return_type, children, self._name)

    device_supported = False

    def eval_cpu(self, table: HostTable) -> HostColumn:
        kids = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        is_str = isinstance(self._return_type, T.StringType)
        out = (np.empty(n, dtype=object) if is_str
               else np.zeros(n, dtype=self._return_type.np_dtype))
        validity = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            if all(k.validity[i] for k in kids):
                v = self.fn(*[
                    k.data[i].item() if hasattr(k.data[i], "item")
                    else k.data[i] for k in kids])
                if v is not None:
                    out[i] = v
                    validity[i] = True
        return HostColumn(self._return_type, out, validity)

    def __repr__(self):
        return f"{self._name}({', '.join(map(repr, self.children))})"


def _extract_body(fn: Callable):
    """(param names, body AST) of a lambda or single-return function."""
    try:
        source = textwrap.dedent(inspect.getsource(fn)).strip()
    except (OSError, TypeError) as e:
        raise UdfCompileError(f"source unavailable: {e}")
    try:
        tree = ast.parse(source)
    except SyntaxError:
        # a lambda embedded in a larger expression (e.g. a call argument)
        # may not parse standalone; find it inside a wrapping parse
        try:
            tree = ast.parse(f"_x_ = {source}")
        except SyntaxError as e:
            raise UdfCompileError(f"unparseable source: {e}")

    lambdas = [n for n in ast.walk(tree) if isinstance(n, ast.Lambda)]
    funcs = [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]
    if fn.__name__ == "<lambda>":
        if len(lambdas) != 1:
            raise UdfCompileError(
                "could not uniquely locate the lambda in its source line")
        node = lambdas[0]
        params = [a.arg for a in node.args.args]
        return params, node.body
    if not funcs:
        raise UdfCompileError("no function definition found in source")
    node = funcs[0]
    body = [s for s in node.body
            if not isinstance(s, (ast.Expr,))]  # skip docstrings
    if len(body) != 1 or not isinstance(body[0], ast.Return) \
            or body[0].value is None:
        raise UdfCompileError(
            "only single-expression functions (one return statement) "
            "compile; everything else falls back to the row-wise path")
    params = [a.arg for a in node.args.args]
    return params, body[0].value


_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Div: lambda a, b: a / b,
}

_CMPOPS = {
    ast.Eq: lambda a, b: a == b,
    ast.NotEq: lambda a, b: a != b,
    ast.Lt: lambda a, b: a < b,
    ast.LtE: lambda a, b: a <= b,
    ast.Gt: lambda a, b: a > b,
    ast.GtE: lambda a, b: a >= b,
}


def _translate(node: ast.AST, env: dict) -> Expression:
    from spark_rapids_tpu.ops.arithmetic import Abs, Pmod
    from spark_rapids_tpu.ops.conditional import If
    from spark_rapids_tpu.ops.math import Pow, Round
    from spark_rapids_tpu.ops.predicates import Not
    from spark_rapids_tpu.ops.strings import (
        EndsWith,
        Length,
        Lower,
        StartsWith,
        StringTrim,
        Upper,
    )

    def rec(n):
        return _translate(n, env)

    if isinstance(node, ast.Constant):
        if node.value is None or isinstance(node.value, (bool, int, float,
                                                         str)):
            return lit(node.value)
        raise UdfCompileError(f"unsupported constant {node.value!r}")
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise UdfCompileError(f"free variable {node.id!r} "
                              "(closures don't compile)")
    if isinstance(node, ast.BinOp):
        op = type(node.op)
        if op in _BINOPS:
            return _BINOPS[op](rec(node.left), rec(node.right))
        if op is ast.Mod:
            # Python % sign rule == Spark pmod
            return Pmod(rec(node.left), rec(node.right))
        if op is ast.Pow:
            return Pow(rec(node.left), rec(node.right))
        raise UdfCompileError(f"operator {op.__name__} does not compile")
    if isinstance(node, ast.UnaryOp):
        if isinstance(node.op, ast.USub):
            return -rec(node.operand)
        if isinstance(node.op, ast.Not):
            return Not(rec(node.operand))
        raise UdfCompileError("unsupported unary operator")
    if isinstance(node, ast.Compare):
        left = node.left
        parts = []
        for op, comp in zip(node.ops, node.comparators):
            if type(op) not in _CMPOPS:
                raise UdfCompileError(
                    f"comparison {type(op).__name__} does not compile")
            parts.append(_CMPOPS[type(op)](rec(left), rec(comp)))
            left = comp
        out = parts[0]
        for p in parts[1:]:
            out = out & p
        return out
    if isinstance(node, ast.BoolOp):
        vals = [rec(v) for v in node.values]
        out = vals[0]
        for v in vals[1:]:
            out = (out & v) if isinstance(node.op, ast.And) else (out | v)
        return out
    if isinstance(node, ast.IfExp):
        return If(rec(node.test), rec(node.body), rec(node.orelse))
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name):
            fname = node.func.id
            args = [rec(a) for a in node.args]
            if fname == "abs" and len(args) == 1:
                return Abs(args[0])
            if fname == "len" and len(args) == 1:
                return Length(args[0])
            if fname in ("min", "max"):
                # SQL Least/Greatest SKIP nulls while Python min/max (and
                # the row-wise fallback) would not — reject rather than
                # compile to divergent semantics (the reference's rule:
                # compile only when exactly equivalent)
                raise UdfCompileError(
                    f"{fname}() null semantics differ from SQL "
                    "Least/Greatest; use F.least/F.greatest explicitly")
            if fname == "round" and len(args) in (1, 2):
                scale = args[1] if len(args) == 2 else lit(0)
                if not isinstance(scale, Literal):
                    raise UdfCompileError("round scale must be constant")
                # Python round is banker's; Spark round is HALF_UP —
                # BRound matches Python
                from spark_rapids_tpu.ops.math import BRound
                return BRound(args[0], scale)
            raise UdfCompileError(f"call to {fname}() does not compile")
        if isinstance(node.func, ast.Attribute):
            target = rec(node.func.value)
            m = node.func.attr
            args = [rec(a) for a in node.args]
            if m == "upper" and not args:
                return Upper(target)
            if m == "lower" and not args:
                return Lower(target)
            if m == "strip" and not args:
                return StringTrim(target)
            if m == "startswith" and len(args) == 1:
                return StartsWith(target, args[0])
            if m == "endswith" and len(args) == 1:
                return EndsWith(target, args[0])
            raise UdfCompileError(f".{m}() does not compile")
    raise UdfCompileError(f"AST node {type(node).__name__} does not compile")


class udf:
    """Decorator/factory: ``F.udf(lambda x: x * 2 + 1)`` returns a callable
    producing an ENGINE EXPRESSION when the body compiles, else a row-wise
    PythonUDF fallback (return_type then required)."""

    def __init__(self, fn: Callable, return_type: Optional[T.DataType] = None):
        self.fn = fn
        self.return_type = return_type
        self._params = None
        self._body = None
        self._reason = None
        try:
            self._params, self._body = _extract_body(fn)
        except UdfCompileError as e:
            self._reason = str(e)

    @property
    def compiled(self) -> bool:
        return self._body is not None

    def __call__(self, *cols) -> Expression:
        args = [c if isinstance(c, Expression) else lit(c) for c in cols]
        if self._body is not None:
            if len(args) != len(self._params):
                raise TypeError(
                    f"udf takes {len(self._params)} args, got {len(args)}")
            try:
                return _translate(self._body, dict(zip(self._params, args)))
            except UdfCompileError as e:
                self._reason = str(e)
        if self.return_type is None:
            raise UdfCompileError(
                f"UDF does not compile ({self._reason}) and no return_type "
                "was given for the row-wise fallback")
        warnings.warn(
            f"UDF {getattr(self.fn, '__name__', '<lambda>')} does not "
            f"compile to engine expressions ({self._reason}); falling back "
            "to row-wise CPU execution", RuntimeWarning, stacklevel=2)
        return PythonUDF(self.fn, self.return_type, args)


# ---------------------------------------------------------------------------
# Columnar device UDF (RapidsUDF analog)
# ---------------------------------------------------------------------------

class ColumnarDeviceUDF(Expression):
    """User-implemented COLUMNAR UDF running fused on device (reference:
    RapidsUDF.java:70 ``evaluateColumnar(ColumnVector*) -> ColumnVector``,
    checked by GpuUserDefinedFunction/GpuScalaUDF).

    The user function receives one jax array per argument (plus a boolean
    validity array per argument) and returns (data, validity) jax arrays
    of the same length — traced INTO the surrounding kernel, so it fuses
    with the rest of the projection exactly like a built-in. Example::

        def clamp(args, valids):
            (x,), (xv,) = args, valids
            return jnp.clip(x, 0.0, 1.0), xv

        df.select(columnar_udf(clamp, T.DOUBLE, col("v")).alias("c"))
    """

    def __init__(self, fn: Callable, return_type: T.DataType,
                 children: Sequence[Expression], name: str = ""):
        self.fn = fn
        self._return_type = return_type
        self.children = tuple(children)
        self._name = name or getattr(fn, "__name__", "columnar_udf")

    @property
    def data_type(self):
        return self._return_type

    @property
    def name(self):
        return self._name

    def with_children(self, children):
        return ColumnarDeviceUDF(self.fn, self._return_type, children,
                                 self._name)

    def resolve(self, bound_children):
        for c in bound_children:
            if isinstance(c.data_type, T.StringType):
                raise UdfCompileError(
                    "columnar device UDFs cannot take string arguments "
                    "(strings are dictionary codes on device; use the "
                    "row-wise udf() fallback or built-in string functions)")
        return self.with_children(bound_children)

    def key(self):
        # the USER FUNCTION's CODE identifies the traced kernel — keying
        # by code object (not id) lets logically identical lambdas
        # recreated per query share one compiled kernel instead of
        # growing the compile caches unboundedly. Closure VALUES are not
        # in the key: a UDF whose behavior depends on captured mutable
        # state would alias; capture constants only.
        code = getattr(self.fn, "__code__", None)
        fid = (code.co_filename, code.co_firstlineno,
               hash(code.co_code)) if code is not None else id(self.fn)
        return ("columnar_udf", fid, str(self._return_type),
                tuple(c.key() for c in self.children))

    def eval_cpu(self, table):
        import jax.numpy as jnp
        cols = [c.eval_cpu(table) for c in self.children]
        data, validity = self.fn(
            tuple(jnp.asarray(c.data) for c in cols),
            tuple(jnp.asarray(c.validity) for c in cols))
        return HostColumn(self._return_type,
                          np.asarray(data).astype(
                              self._return_type.np_dtype),
                          np.asarray(validity).astype(np.bool_))

    def eval_dev(self, ctx, child_vals, prep):
        from spark_rapids_tpu.ops.expr import DevVal
        data, validity = self.fn(
            tuple(v.data for v in child_vals),
            tuple(v.validity for v in child_vals))
        return DevVal(data, validity)


def columnar_udf(fn: Callable, return_type: T.DataType, *args):
    """Factory for ColumnarDeviceUDF (fixed-width return types only —
    string outputs would need an unbounded dictionary)."""
    from spark_rapids_tpu.ops.expr import col as _col
    if isinstance(return_type, T.StringType):
        raise UdfCompileError(
            "columnar device UDFs must return fixed-width types")
    exprs = [_col(a) if isinstance(a, str) else a for a in args]
    return ColumnarDeviceUDF(fn, return_type, exprs)
