"""P2P shuffle client/server protocol.

Reference (SURVEY.md §2.6): ``RapidsShuffleClient.scala:481`` /
``RapidsShuffleServer.scala:450`` — fetch flow: the client sends a metadata
request for the (shuffle, partition) blocks it needs; the server answers
from its ShuffleBufferCatalog with block ids + sizes; the client then
issues a transfer request and the server streams the blocks through send
bounce buffers in fixed windows (``BufferSendState``), the client
reassembling them via ``BufferReceiveState`` into complete blocks handed
to the received-buffer catalog.

Wire encodings are little-endian struct-packed (the analog of the
reference's flatbuffer metadata messages)."""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Tuple

from spark_rapids_tpu.errors import ColumnarProcessingError, ShuffleFetchError
from spark_rapids_tpu.runtime.faults import FAULTS, fault_point
from spark_rapids_tpu.shuffle.catalogs import (
    BlockId,
    ShuffleBufferCatalog,
    ShuffleReceivedBufferCatalog,
)
from spark_rapids_tpu.shuffle.transport import (
    MSG_ERROR,
    MSG_METADATA_REQ,
    MSG_METADATA_RESP,
    MSG_TRANSFER_REQ,
    TX_SUCCESS,
    BlockRange,
    BounceBufferManager,
    Connection,
    windowed_slices,
)

_META_REQ = struct.Struct("<IIi")          # shuffle_id, partition_id, n_maps
_BLOCK = struct.Struct("<IIIQ")            # shuffle, map, part, length
_XFER_HDR = struct.Struct("<QI")           # window_size, n_blocks
_BLOCK_ID = struct.Struct("<III")


def encode_metadata_request(shuffle_id: int, partition_id: int,
                            map_ids: Optional[List[int]]) -> bytes:
    n = -1 if map_ids is None else len(map_ids)
    out = bytearray(_META_REQ.pack(shuffle_id, partition_id, n))
    for m in (map_ids or ()):
        out += struct.pack("<I", m)
    return bytes(out)


def decode_metadata_request(payload: bytes):
    shuffle_id, partition_id, n = _META_REQ.unpack_from(payload, 0)
    if n < 0:
        return shuffle_id, partition_id, None
    off = _META_REQ.size
    map_ids = [struct.unpack_from("<I", payload, off + 4 * i)[0]
               for i in range(n)]
    return shuffle_id, partition_id, map_ids


def encode_block_list(blocks: List[Tuple[BlockId, int]]) -> bytes:
    out = bytearray(struct.pack("<I", len(blocks)))
    for (sid, mid, pid), length in blocks:
        out += _BLOCK.pack(sid, mid, pid, length)
    return bytes(out)


def decode_block_list(payload: bytes) -> List[Tuple[BlockId, int]]:
    (n,) = struct.unpack_from("<I", payload, 0)
    out = []
    off = 4
    for _ in range(n):
        sid, mid, pid, length = _BLOCK.unpack_from(payload, off)
        out.append(((sid, mid, pid), length))
        off += _BLOCK.size
    return out


def encode_transfer_request(window_size: int,
                            block_ids: List[BlockId]) -> bytes:
    out = bytearray(_XFER_HDR.pack(window_size, len(block_ids)))
    for sid, mid, pid in block_ids:
        out += _BLOCK_ID.pack(sid, mid, pid)
    return bytes(out)


def decode_transfer_request(payload: bytes):
    window_size, n = _XFER_HDR.unpack_from(payload, 0)
    off = _XFER_HDR.size
    ids = []
    for _ in range(n):
        ids.append(_BLOCK_ID.unpack_from(payload, off))
        off += _BLOCK_ID.size
    return window_size, ids


class ShuffleServer:
    """Serves cached shuffle blocks (RapidsShuffleServer analog). Plugged
    into a transport listener (TCP) or the in-process registry."""

    def __init__(self, catalog: ShuffleBufferCatalog,
                 send_pool: BounceBufferManager):
        self.catalog = catalog
        self.send_pool = send_pool
        self.requests_served = 0
        self.windows_sent = 0

    # -- request channel ----------------------------------------------------
    def handle_request(self, msg_type: int, payload: bytes):
        if msg_type != MSG_METADATA_REQ:
            return MSG_ERROR, f"unsupported request type {msg_type}".encode()
        shuffle_id, partition_id, map_ids = decode_metadata_request(payload)
        blocks = self.catalog.blocks_for_partition(
            shuffle_id, partition_id, map_ids)
        self.requests_served += 1
        return MSG_METADATA_RESP, encode_block_list(blocks)

    # -- stream channel (BufferSendState analog) ----------------------------
    def handle_stream(self, msg_type: int,
                      payload: bytes) -> Iterator[memoryview]:
        if msg_type != MSG_TRANSFER_REQ:
            raise ColumnarProcessingError(
                f"unsupported stream type {msg_type}")
        window_size, ids = decode_transfer_request(payload)
        if window_size > self.send_pool.buffer_size:
            raise ColumnarProcessingError(
                f"requested window {window_size}B exceeds server bounce "
                f"buffer {self.send_pool.buffer_size}B")
        blocks = []
        for bid in ids:
            length = self.catalog.block_length(bid)
            if length is None:
                raise ColumnarProcessingError(
                    f"unknown shuffle block {bid}")
            blocks.append(BlockRange(bid, length))
        for window in windowed_slices(blocks, window_size):
            buf = self.send_pool.acquire()
            try:
                fill = 0
                for ws in window:
                    data = self.catalog.get_block(blocks[ws.block_index]
                                                  .block_id)
                    buf[fill:fill + ws.length] = \
                        data[ws.block_offset:ws.block_offset + ws.length]
                    fill += ws.length
                self.windows_sent += 1
                yield memoryview(buf)[:fill]
            finally:
                self.send_pool.release(buf)


class ShuffleClient:
    """Fetches a reduce partition's blocks from one peer
    (RapidsShuffleClient analog)."""

    def __init__(self, connection: Connection, window_size: int = 1 << 20):
        self.connection = connection
        self.window_size = window_size

    def fetch_metadata(self, shuffle_id: int, partition_id: int,
                       map_ids: Optional[List[int]] = None
                       ) -> List[Tuple[BlockId, int]]:
        fault_point("shuffle.fetch.metadata")
        tx = self.connection.request(
            MSG_METADATA_REQ,
            encode_metadata_request(shuffle_id, partition_id, map_ids))
        if tx.status != TX_SUCCESS:
            # retryable: the peer may be transiently overloaded or the
            # connection desynced — the fetch-retry loop reconnects
            raise ShuffleFetchError(
                f"metadata fetch failed: {tx.error_message}")
        return decode_block_list(tx.payload)

    def fetch_blocks(self, blocks: List[Tuple[BlockId, int]],
                     received: ShuffleReceivedBufferCatalog):
        """Stream the given blocks; completed blocks land in ``received``
        in arrival order (BufferReceiveState reassembly)."""
        if not blocks:
            received.expect(0)
            return
        received.expect(len(blocks))
        # one buffer per in-flight block, handed over (not retained) on
        # completion — client memory is bounded by the bounce pool plus the
        # single block being assembled, not the whole partition
        state = {"next_block": 0, "block_filled": 0,
                 "buf": bytearray(blocks[0][1])}

        def on_window(view: memoryview):
            consumed = 0
            while consumed < len(view):
                i = state["next_block"]
                if i >= len(blocks):
                    raise ColumnarProcessingError(
                        "server sent more bytes than requested")
                _bid, length = blocks[i]
                take = min(len(view) - consumed,
                           length - state["block_filled"])
                start = state["block_filled"]
                state["buf"][start:start + take] = \
                    view[consumed:consumed + take]
                state["block_filled"] += take
                consumed += take
                if state["block_filled"] == length:
                    blob = bytes(state["buf"])
                    if FAULTS.armed:
                        # corrupt kind damages the completed block; the
                        # TPAK CRC catches it at deserialization and the
                        # fetch retries
                        blob = fault_point("shuffle.fetch.stream",
                                           data=blob)
                    received.add(blocks[i][0], blob)
                    state["next_block"] += 1
                    state["block_filled"] = 0
                    if state["next_block"] < len(blocks):
                        state["buf"] = bytearray(
                            blocks[state["next_block"]][1])

        fault_point("shuffle.fetch.stream")
        tx = self.connection.stream(
            MSG_TRANSFER_REQ,
            encode_transfer_request(self.window_size,
                                    [bid for bid, _ in blocks]),
            on_window)
        if tx.status != TX_SUCCESS:
            received.fail(tx.error_message or "transfer failed")
            raise ShuffleFetchError(
                f"block transfer failed: {tx.error_message}")
        if state["next_block"] != len(blocks):
            received.fail("short transfer")
            raise ShuffleFetchError(
                f"short transfer: {state['next_block']}/{len(blocks)} blocks")

    def fetch_partition(self, shuffle_id: int, partition_id: int,
                        received: ShuffleReceivedBufferCatalog,
                        map_ids: Optional[List[int]] = None
                        ) -> List[Tuple[BlockId, int]]:
        """Metadata round trip + streamed transfer; returns the block list
        (what the reference's RapidsShuffleIterator drives per peer)."""
        from spark_rapids_tpu.obs.spans import span
        with span("shuffle.fetch", cat="shuffle",
                  shuffle=shuffle_id, partition=partition_id):
            blocks = self.fetch_metadata(shuffle_id, partition_id, map_ids)
            self.fetch_blocks(blocks, received)
        return blocks
