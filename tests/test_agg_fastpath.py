"""Dictionary-code aggregation fast path + split-f64 sums + input fusion
(reference analog: hash_aggregate_test.py; the fast path is the TPU-first
no-sort grouping of execs/aggregate.py, split sums are ops/segsum.py)."""

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit

from tests.asserts import assert_tpu_and_cpu_are_equal
from tests.data_gen import (
    BooleanGen, DoubleGen, IntGen, LongGen, StringGen, gen_table,
)


def _df(sess, gens, n=800, seed=11, num_batches=1):
    from spark_rapids_tpu.plan import from_host_table
    return from_host_table(gen_table(gens, n, seed), sess, num_batches)


GENS = {"s": StringGen(cardinality=7), "b": BooleanGen(),
        "v": LongGen(min_val=-1000, max_val=1000), "d": DoubleGen()}

ALL_AGGS = [
    F.count().alias("cnt"), F.count(col("v")).alias("cntv"),
    F.sum(col("v")).alias("sumv"), F.sum(col("d")).alias("sumd"),
    F.avg(col("d")).alias("avgd"), F.min(col("d")).alias("mind"),
    F.max(col("v")).alias("maxv"), F.first(col("v")).alias("fv"),
    F.last(col("d")).alias("ld"),
]


@pytest.fixture(scope="module")
def split_session():
    """Force the split-f64 sum path even on the CPU backend."""
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.tpu.sum.splitF64": "true"})


@pytest.fixture(scope="module")
def sorted_session():
    """Disable the dict fast path to pin the sort-segment path."""
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.tpu.agg.maxDictGroups": "0"})


def test_fast_path_string_key(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("s").agg(*ALL_AGGS),
        session, cpu_session)


def test_fast_path_bool_key(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("b").agg(*ALL_AGGS),
        session, cpu_session)


def test_fast_path_string_bool_multi_key(session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("s", "b").agg(*ALL_AGGS),
        session, cpu_session)


def test_fast_path_matches_sorted_path(session, sorted_session):
    """The no-sort dict path and the general sort-segment path must agree."""
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("s", "b").agg(*ALL_AGGS),
        session, sorted_session)


def test_fast_path_with_fused_filter_project(session, cpu_session):
    def build(s):
        return (
            _df(s, GENS)
            .filter(col("v") > lit(-500))
            .select(col("s"), col("b"), col("v"),
                    (col("d") * lit(2.0)).alias("d2"))
            .filter(col("v") < lit(500))
            .group_by("s", "b")
            .agg(F.count().alias("cnt"), F.sum(col("d2")).alias("sd2"),
                 F.avg(col("v")).alias("av"))
        )
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_fusion_peels_project_and_filter(session):
    """The converted exec tree should contain no Project/Filter above the
    scan once fusion inlines them into the aggregate."""
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec
    from spark_rapids_tpu.execs.basic import TpuFilterExec, TpuProjectExec

    df = (_df(session, GENS)
          .filter(col("v") > lit(0))
          .select(col("s"), (col("d") + lit(1.0)).alias("d1"))
          .group_by("s").agg(F.sum(col("d1")).alias("sd")))
    executable, _ = apply_overrides(df.plan, session.conf)

    aggs, others = [], []

    def walk(e):
        if isinstance(e, TpuHashAggregateExec):
            aggs.append(e)
        if isinstance(e, (TpuFilterExec, TpuProjectExec)):
            others.append(e)
        for c in getattr(e, "children", ()):
            walk(c)
        for attr in ("source", "tpu_exec", "cpu_node"):
            nxt = getattr(e, attr, None)
            if nxt is not None:
                walk(nxt)

    walk(executable)
    assert len(aggs) == 1
    assert aggs[0].filters, "filter should be fused into the aggregate"
    assert not others, f"unfused execs remain: {others}"


def test_split_sum_accuracy(split_session, cpu_session):
    """Split-f64 sums must stay within ~1e-7 relative of the exact path."""
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, {"s": StringGen(cardinality=5), "d": DoubleGen()},
                      n=5000)
        .group_by("s").agg(F.sum(col("d")).alias("sd"),
                           F.avg(col("d")).alias("ad")),
        split_session, cpu_session, approximate_float=True)


def test_split_sum_huge_values_reroute_exact(split_session, cpu_session):
    """|x| > 1e34 must reroute to the exact path at runtime (lax.cond)."""
    from spark_rapids_tpu.plan import from_host_table
    from spark_rapids_tpu.columnar import HostColumn, HostTable
    from spark_rapids_tpu import types as T

    n = 512
    vals = np.full(n, 1e300)
    vals[::2] = -1e300
    vals[0] = 12345.0
    keys = np.array(["a"] * n, dtype=object)
    table = HostTable(["s", "d"], [HostColumn(T.STRING, keys),
                                   HostColumn(T.DOUBLE, vals)])

    def build(s):
        return from_host_table(table, s).group_by("s").agg(
            F.sum(col("d")).alias("sd"))

    assert_tpu_and_cpu_are_equal(build, split_session, cpu_session)


def test_split_segment_sum_unit():
    """Direct unit check of segment_sum_f64 against numpy, forced split."""
    import jax.numpy as jnp
    from spark_rapids_tpu.ops.segsum import segment_sum_f64

    rng = np.random.default_rng(3)
    cap = 4096
    vals = rng.random(cap) * 1e5 - 5e4
    gid = (rng.random(cap) * 11).astype(np.int32)
    got = np.asarray(segment_sum_f64(
        jnp.asarray(vals), jnp.asarray(gid), 16, cap, use_split=True))
    ref = np.zeros(16)
    np.add.at(ref, gid, vals)
    np.testing.assert_allclose(got, ref, rtol=1e-7, atol=1e-4)


def test_sorted_path_nulls_in_keys(session, cpu_session):
    gens = {"s": StringGen(cardinality=4), "b": BooleanGen(),
            "v": IntGen(min_val=-50, max_val=50, null_prob=0.3)}
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, gens).group_by("s", "b").agg(
            F.count().alias("c"), F.sum(col("v")).alias("sv")),
        session, cpu_session)


def test_large_dict_falls_back_to_sorted(session, cpu_session):
    """Key domain above maxDictGroups must take the sort-segment path and
    still be correct."""
    from spark_rapids_tpu.session import TpuSession
    limited = TpuSession({"spark.rapids.tpu.agg.maxDictGroups": 4})
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("s").agg(F.count().alias("c")),
        limited, cpu_session)


def test_unblocked_split_guard_skewed_segment():
    """A single huge all-positive segment must reroute to the exact path:
    the unblocked split guard scales with per-segment row count (review
    fix — a mass-only guard calibrated for 1024-row blocks under-counts
    sqrt(n/1024)x)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from spark_rapids_tpu.ops.segsum import _unblocked_split_segment_sum

    n = 1 << 17
    v = jnp.asarray(np.full(n, 1.0 + 2**-26))  # low bits shred in f32 sums
    gid = jnp.zeros(n, dtype=jnp.int32)
    got = jax.jit(
        lambda v, g: _unblocked_split_segment_sum(v, g, n))(v, gid)
    want = jax.ops.segment_sum(v, gid, num_segments=n)
    rel = abs(float(got[0]) - float(want[0])) / float(want[0])
    assert rel <= 1e-6, rel


def test_ungrouped_agg_fast_path_empty_input(session):
    """Global aggregates yield exactly ONE row on empty input: count=0,
    sum NULL (Spark semantics through the new zero-key fast path)."""
    import numpy as np

    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col, lit

    df = (session.create_dataframe(
        {"v": np.arange(50, dtype=np.int64)})
        .filter(col("v") > lit(10**9))
        .agg(F.count("v").alias("c"), F.sum("v").alias("s"),
             F.avg("v").alias("a"), F.max("v").alias("m")))
    rows = df.collect()
    assert rows == [(0, None, None, None)]
