"""Expression base classes and the device compilation machinery."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator, List, NamedTuple, Optional, Sequence, Tuple

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable, HostColumn, HostTable, bucket_for
from spark_rapids_tpu.errors import ColumnarProcessingError, UnsupportedOnTpu


class DevVal(NamedTuple):
    """A traced intermediate: data array + validity array (bool)."""

    data: jax.Array
    validity: jax.Array


@dataclass
class NodePrep:
    """Host-side per-batch preparation result for one expression node."""

    out_dict: Optional[np.ndarray] = None  # dictionary if output is STRING
    dict_sorted: bool = True
    aux_slots: Tuple[int, ...] = ()
    extra: dict = field(default_factory=dict)
    #: (min, max) bound on valid values of an integer-family output
    #: (DeviceColumn.domain carried through prep; per-batch data, NOT part
    #: of the trace key — consumers must feed the bounds in as device
    #: operands, never bake them into the trace)
    out_domain: Optional[Tuple[int, int]] = None


class PrepCtx:
    """Accumulates auxiliary device inputs during the host prep pass."""

    def __init__(self, table: DeviceTable):
        self.table = table
        self.aux_arrays: List[np.ndarray] = []
        self.aux_intern: List[bool] = []

    def add_aux(self, arr: np.ndarray, intern: bool = True) -> int:
        """Register a host array as a device input, padded (on the leading
        dim) to a bucket so that compiled programs are shared across batches
        with different dictionary sizes."""
        n = len(arr)
        cap = bucket_for(max(n, 1))
        if cap != n:
            padded = np.zeros((cap,) + arr.shape[1:], dtype=arr.dtype)
            padded[:n] = arr
            arr = padded
        self.aux_arrays.append(arr)
        self.aux_intern.append(intern)
        return len(self.aux_arrays) - 1


class EvalCtx:
    """Traced-side context handed to eval_dev. ``live`` carries a masked
    batch's liveness (DeviceTable.live); row-position semantics stay
    slot-based either way."""

    def __init__(self, cols: Sequence[DevVal], aux: Sequence[jax.Array],
                 nrows: jax.Array, capacity: int, live=None,
                 ansi: bool = False):
        self.cols = tuple(cols)
        self.aux = tuple(aux)
        self.nrows = nrows
        self.capacity = capacity
        self.live = live
        #: ANSI mode: expressions append (label, device bool flag) pairs
        #: for violations in LIVE rows; the hosting kernel returns them
        self.ansi = ansi
        self.ansi_errors: List[tuple] = []
        #: branch-selection mask: inside a CASE WHEN / IF branch only the
        #: selected rows may raise (Spark evaluates branches lazily; the
        #: engine evaluates eagerly and guards the error check instead)
        self.ansi_guard = None
        self._prep_iter: Optional[Iterator[NodePrep]] = None

    def ansi_check(self, label: str, bad) -> None:
        """Record an ANSI violation flag (True anywhere = error). Callers
        pass ``bad`` already masked to valid, live rows."""
        if self.ansi_guard is not None:
            bad = bad & self.ansi_guard
        self.ansi_errors.append(
            (label, jnp.any(bad & self.row_mask())))

    def guarded(self, mask):
        """Context manager scoping ansi_check to ``mask``-selected rows
        (composes with an enclosing guard for nested conditionals)."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            prev = self.ansi_guard
            self.ansi_guard = mask if prev is None else (prev & mask)
            try:
                yield
            finally:
                self.ansi_guard = prev
        return cm()

    def next_prep(self) -> NodePrep:
        return next(self._prep_iter)  # type: ignore[arg-type]

    def row_mask(self) -> jax.Array:
        if self.live is not None:
            return self.live
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.nrows


class Expression:
    """Base expression. Subclasses set ``children`` and implement the three
    evaluation paths. Expressions are immutable; ``with_children`` rebuilds."""

    children: Tuple["Expression", ...] = ()

    #: True for expressions whose value depends on a row's physical slot
    #: (monotonically_increasing_id, rand): masked batches must compact
    #: before evaluating them so slot numbering matches the prefix form
    position_dependent = False

    # --- static properties -------------------------------------------------
    @property
    def data_type(self) -> T.DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return any(c.nullable for c in self.children) if self.children else True

    @property
    def name(self) -> str:
        return type(self).__name__

    def with_children(self, children: Sequence["Expression"]) -> "Expression":
        raise NotImplementedError(type(self).__name__)

    def key(self) -> tuple:
        """Structural key for the compile cache. Must capture everything
        that changes the traced computation (not per-batch data)."""
        return (self.name, tuple(c.key() for c in self.children))

    def __repr__(self):
        args = ", ".join(repr(c) for c in self.children)
        return f"{self.name}({args})"

    # --- binding -----------------------------------------------------------
    def bind(self, schema: Sequence[Tuple[str, T.DataType]]) -> "Expression":
        bound = [c.bind(schema) for c in self.children]
        return self.resolve(bound)

    def resolve(self, bound_children: Sequence["Expression"]) -> "Expression":
        """Hook for type coercion: may insert casts or rewrite. Default:
        rebuild with bound children."""
        return self.with_children(bound_children)

    # --- CPU path (Spark-exact oracle) ------------------------------------
    def eval_cpu(self, table: HostTable) -> HostColumn:
        raise NotImplementedError(f"{self.name}.eval_cpu")

    # --- device path -------------------------------------------------------
    def prep(self, pctx: PrepCtx, child_preps: Sequence[NodePrep]) -> NodePrep:
        return NodePrep()

    def eval_dev(self, ctx: EvalCtx, child_vals: Sequence[DevVal],
                 prep: NodePrep) -> DevVal:
        raise UnsupportedOnTpu(f"{self.name} has no device implementation")

    #: False for expressions that only have a CPU path; the overrides layer
    #: uses this to tag fallbacks.
    device_supported: bool = True

    # --- operator sugar for the DataFrame API ------------------------------
    def _bin(self, opcls, other, reflect=False):
        other = other if isinstance(other, Expression) else Literal.of(other)
        return opcls(other, self) if reflect else opcls(self, other)

    def __add__(self, o):
        from spark_rapids_tpu.ops.arithmetic import Add
        return self._bin(Add, o)

    def __radd__(self, o):
        from spark_rapids_tpu.ops.arithmetic import Add
        return self._bin(Add, o, True)

    def __sub__(self, o):
        from spark_rapids_tpu.ops.arithmetic import Subtract
        return self._bin(Subtract, o)

    def __rsub__(self, o):
        from spark_rapids_tpu.ops.arithmetic import Subtract
        return self._bin(Subtract, o, True)

    def __mul__(self, o):
        from spark_rapids_tpu.ops.arithmetic import Multiply
        return self._bin(Multiply, o)

    def __rmul__(self, o):
        from spark_rapids_tpu.ops.arithmetic import Multiply
        return self._bin(Multiply, o, True)

    def __truediv__(self, o):
        from spark_rapids_tpu.ops.arithmetic import Divide
        return self._bin(Divide, o)

    def __mod__(self, o):
        from spark_rapids_tpu.ops.arithmetic import Remainder
        return self._bin(Remainder, o)

    def __neg__(self):
        from spark_rapids_tpu.ops.arithmetic import UnaryMinus
        return UnaryMinus(self)

    def __eq__(self, o):  # type: ignore[override]
        from spark_rapids_tpu.ops.predicates import EqualTo
        return self._bin(EqualTo, o)

    def __ne__(self, o):  # type: ignore[override]
        from spark_rapids_tpu.ops.predicates import EqualTo, Not
        return Not(self._bin(EqualTo, o))

    def __lt__(self, o):
        from spark_rapids_tpu.ops.predicates import LessThan
        return self._bin(LessThan, o)

    def __le__(self, o):
        from spark_rapids_tpu.ops.predicates import LessThanOrEqual
        return self._bin(LessThanOrEqual, o)

    def __gt__(self, o):
        from spark_rapids_tpu.ops.predicates import GreaterThan
        return self._bin(GreaterThan, o)

    def __ge__(self, o):
        from spark_rapids_tpu.ops.predicates import GreaterThanOrEqual
        return self._bin(GreaterThanOrEqual, o)

    def __and__(self, o):
        from spark_rapids_tpu.ops.predicates import And
        return self._bin(And, o)

    def __or__(self, o):
        from spark_rapids_tpu.ops.predicates import Or
        return self._bin(Or, o)

    def __invert__(self):
        from spark_rapids_tpu.ops.predicates import Not
        return Not(self)

    def __hash__(self):
        return hash(self.key())

    def alias(self, name: str) -> "Alias":
        return Alias(self, name)

    def cast(self, dtype) -> "Expression":
        from spark_rapids_tpu.ops.cast import Cast
        if isinstance(dtype, str):
            dtype = T.parse_type(dtype)
        return Cast(self, dtype)

    def isnull(self):
        from spark_rapids_tpu.ops.predicates import IsNull
        return IsNull(self)

    def isnotnull(self):
        from spark_rapids_tpu.ops.predicates import IsNotNull
        return IsNotNull(self)


class AttributeReference(Expression):
    """Unresolved column-by-name (pre-binding)."""

    def __init__(self, col_name: str):
        self.col_name = col_name

    @property
    def name(self):
        return f"'{self.col_name}"

    @property
    def data_type(self):
        raise ColumnarProcessingError(f"unresolved attribute {self.col_name}")

    def key(self):
        return ("attr", self.col_name)

    def bind(self, schema):
        for i, (n, dt) in enumerate(schema):
            if n == self.col_name:
                return BoundReference(i, dt, name_hint=self.col_name)
        raise ColumnarProcessingError(
            f"column {self.col_name!r} not in {[n for n, _ in schema]}")

    def __repr__(self):
        return f"col({self.col_name!r})"


class BoundReference(Expression):
    """Input column by ordinal (post-binding)."""

    def __init__(self, ordinal: int, dtype: T.DataType, nullable_: bool = True,
                 name_hint: str = ""):
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable_
        self.name_hint = name_hint

    @property
    def data_type(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    def key(self):
        return ("ref", self.ordinal, str(self._dtype))

    def with_children(self, children):
        return self

    def eval_cpu(self, table: HostTable) -> HostColumn:
        return table.columns[self.ordinal]

    def prep(self, pctx: PrepCtx, child_preps) -> NodePrep:
        c = pctx.table.columns[self.ordinal]
        # lambda-scope evaluation binds SimpleNamespace pseudo-columns
        # (ops/nested.py), hence getattr
        return NodePrep(out_dict=c.dictionary, dict_sorted=c.dict_sorted,
                        out_domain=getattr(c, "domain", None))

    def eval_dev(self, ctx: EvalCtx, child_vals, prep) -> DevVal:
        return ctx.cols[self.ordinal]

    def __repr__(self):
        return f"#{self.ordinal}:{self._dtype}"


class Literal(Expression):
    def __init__(self, value, dtype: Optional[T.DataType] = None):
        self._dtype = dtype if dtype is not None else T.python_to_spark_type(value)
        # temporal literals normalize to the INTERNAL representation
        # (days / UTC micros) at construction so both eval paths fill
        # plain ints
        import datetime as _dt
        if isinstance(value, _dt.datetime):
            epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
            v = value if value.tzinfo is not None else \
                value.replace(tzinfo=_dt.timezone.utc)
            value = (v - epoch) // _dt.timedelta(microseconds=1)
        elif isinstance(value, _dt.date):
            value = (value - _dt.date(1970, 1, 1)).days
        self.value = value

    @staticmethod
    def of(value, dtype: Optional[T.DataType] = None) -> "Literal":
        return Literal(value, dtype)

    @property
    def data_type(self):
        return self._dtype

    @property
    def nullable(self):
        return self.value is None

    def key(self):
        # literal VALUE is part of the traced constant, so it is in the key;
        # string literals trace as code 0 over a 1-entry dict, so only
        # null-ness matters for them.
        if isinstance(self._dtype, T.StringType):
            return ("lit", "str", self.value is None)
        return ("lit", str(self._dtype), self.value)

    def with_children(self, children):
        return self

    def eval_cpu(self, table: HostTable) -> HostColumn:
        n = table.num_rows
        validity = np.full(n, self.value is not None, dtype=np.bool_)
        if isinstance(self._dtype, T.StringType):
            data = np.full(n, self.value, dtype=object)
        else:
            fill = self.value if self.value is not None else 0
            data = np.full(n, fill, dtype=self._dtype.np_dtype)
        return HostColumn(self._dtype, data, validity)

    def prep(self, pctx: PrepCtx, child_preps) -> NodePrep:
        if isinstance(self._dtype, T.StringType) and self.value is not None:
            return NodePrep(out_dict=np.array([self.value], dtype=object))
        return NodePrep()

    def eval_dev(self, ctx: EvalCtx, child_vals, prep) -> DevVal:
        cap = ctx.capacity
        if isinstance(self._dtype, T.StringType):
            data = jnp.zeros(cap, dtype=jnp.int32)
        else:
            fill = self.value if self.value is not None else 0
            data = jnp.full(cap, fill, dtype=self._dtype.np_dtype)
        validity = jnp.full(cap, self.value is not None, dtype=jnp.bool_)
        return DevVal(data, validity)

    def __repr__(self):
        return f"lit({self.value!r})"


class Alias(Expression):
    def __init__(self, child: Expression, out_name: str):
        self.children = (child,)
        self.out_name = out_name

    @property
    def data_type(self):
        return self.children[0].data_type

    @property
    def nullable(self):
        return self.children[0].nullable

    def key(self):
        return ("alias", self.children[0].key())

    def with_children(self, children):
        return Alias(children[0], self.out_name)

    def eval_cpu(self, table):
        return self.children[0].eval_cpu(table)

    def prep(self, pctx, child_preps):
        return child_preps[0]

    def eval_dev(self, ctx, child_vals, prep):
        return child_vals[0]

    def __repr__(self):
        return f"{self.children[0]!r} AS {self.out_name}"


def col(name: str) -> AttributeReference:
    return AttributeReference(name)


def lit(value, dtype: Optional[T.DataType] = None) -> Literal:
    return Literal(value, dtype)


def output_name(expr: Expression, default: str) -> str:
    if isinstance(expr, Alias):
        return expr.out_name
    if isinstance(expr, AttributeReference):
        return expr.col_name
    if isinstance(expr, BoundReference) and expr.name_hint:
        return expr.name_hint
    return default


def bind(expr: Expression, schema: Sequence[Tuple[str, T.DataType]]) -> Expression:
    return expr.bind(schema)


# ---------------------------------------------------------------------------
# Evaluation drivers
# ---------------------------------------------------------------------------

def evaluate_cpu(exprs: Sequence[Expression], table: HostTable,
                 names: Optional[Sequence[str]] = None) -> HostTable:
    """Project on the CPU path."""
    out_names = list(names) if names else [
        output_name(e, f"col{i}") for i, e in enumerate(exprs)]
    return HostTable(out_names, [e.eval_cpu(table) for e in exprs])


def _walk_prep(expr: Expression, pctx: PrepCtx, out: List[NodePrep]) -> NodePrep:
    child_preps = [_walk_prep(c, pctx, out) for c in expr.children]
    p = expr.prep(pctx, child_preps)
    out.append(p)
    return p


def _walk_eval(expr: Expression, ctx: EvalCtx) -> DevVal:
    walk = getattr(expr, "eval_walk", None)
    if walk is not None:
        # conditionals control their own child evaluation (branch guards);
        # they must consume preps in the standard post-order
        return walk(ctx)
    child_vals = [_walk_eval(c, ctx) for c in expr.children]
    p = ctx.next_prep()
    return expr.eval_dev(ctx, child_vals, p)


def _prep_trace_key(preps: List[NodePrep]) -> tuple:
    """Everything in a NodePrep that eval_dev may consume at TRACE time.

    Contract for eval_dev implementations: per-batch data (dictionary
    contents, literal codes, remap tables, hashes...) must flow through aux
    arrays; only aux slot assignment and items recorded in ``extra`` may
    shape the trace. This is what makes the jit cache sound across batches."""
    return tuple(
        (p.aux_slots, p.out_dict is not None, p.dict_sorted,
         tuple(sorted(p.extra.items())))
        for p in preps
    )


class CompiledProject:
    """A fused, jitted projection of one or more expression trees over a
    device table. Reused across batches via ProjectCache; within one
    CompiledProject, jitted traces are cached per (capacity, prep structure)
    and jax.jit's signature cache handles aux shapes/dtypes."""

    def __init__(self, exprs: Sequence[Expression]):
        self.exprs = tuple(exprs)
        self._traces = {}

    def _get_traced(self, capacity: int, all_preps: List[List[NodePrep]],
                    has_mask: bool, ansi: bool):
        tkey = (capacity, has_mask, ansi,
                tuple(_prep_trace_key(p) for p in all_preps))
        got = self._traces.get(tkey)
        if got is None:
            exprs = self.exprs
            labels: List[str] = []  # filled at trace time, stable per key

            def traced(cols, aux, nrows, live):
                outs = []
                errs = []
                for e, preps in zip(exprs, all_preps):
                    ctx = EvalCtx(cols, aux, nrows, capacity, live=live,
                                  ansi=ansi)
                    ctx._prep_iter = iter(preps)
                    outs.append(_walk_eval(e, ctx))
                    errs.extend(ctx.ansi_errors)
                labels.clear()
                labels.extend(lbl for lbl, _ in errs)
                return outs, tuple(f for _, f in errs)

            got = (tpu_jit(traced), labels)
            self._traces[tkey] = got
        return got

    def __call__(self, table: DeviceTable) -> List[DeviceColumn]:
        from spark_rapids_tpu.dispatch import ANSI_MODE, prep_aux
        pctx = PrepCtx(table)
        all_preps: List[List[NodePrep]] = []
        for e in self.exprs:
            preps: List[NodePrep] = []
            _walk_prep(e, pctx, preps)
            all_preps.append(preps)
        col_arrays = tuple(DevVal(c.data, c.validity) for c in table.columns)
        aux_arrays = prep_aux(pctx)

        fn, labels = self._get_traced(table.capacity, all_preps,
                                      table.live is not None,
                                      ANSI_MODE.get())
        out_vals, err_flags = fn(col_arrays, aux_arrays, table.nrows_dev,
                                 table.live)
        deliver_ansi_flags(labels, err_flags)

        out_cols = []
        for e, preps, dv in zip(self.exprs, all_preps, out_vals):
            root_prep = preps[-1]
            out_cols.append(DeviceColumn(
                e.data_type, dv.data, dv.validity,
                dictionary=root_prep.out_dict, dict_sorted=root_prep.dict_sorted,
                domain=root_prep.out_domain))
        return out_cols


def deliver_ansi_flags(labels, err_flags) -> None:
    """Route a kernel's ANSI violation flags: through the speculation
    context (rides the collect's packed fetch — zero extra round trips)
    when one is active, else one immediate device check."""
    if not err_flags:
        return
    from spark_rapids_tpu.runtime import speculation as spec
    ctx = spec.current()
    if ctx is not None:
        for lbl, f in zip(labels, err_flags):
            ctx.add_flag("ansi:" + lbl, f)
        return
    from spark_rapids_tpu.dispatch import host_fetch
    vals = host_fetch(jnp.stack(list(err_flags)))
    spec.check_flag_values(["ansi:" + l for l in labels], vals)


class ProjectCache:
    """Compile cache keyed by (expr keys, schema key). The jitted function
    inside CompiledProject further caches per (bucket, aux shapes) thanks to
    jax.jit's own signature cache."""

    def __init__(self):
        self._cache = {}

    def get(self, exprs: Sequence[Expression], table: DeviceTable) -> CompiledProject:
        key = (tuple(e.key() for e in exprs), table.schema_key()[0])
        cp = self._cache.get(key)
        if cp is None:
            cp = CompiledProject(exprs)
            self._cache[key] = cp
        return cp


_GLOBAL_PROJECT_CACHE = ProjectCache()

#: process-wide cache of jitted exec kernels keyed by STRUCTURE (expression
#: keys + schema + capacity + prep trace keys). Exec instances are per-query,
#: but two queries with the same shape must share one trace/compile — without
#: this every query re-traces and re-fetches from the compile cache (the
#: XLA analog of cuDF's precompiled kernels, SURVEY.md §7).
_GLOBAL_KERNEL_CACHE: dict = {}


def cached_kernel(key: tuple, build):
    """Return the jitted kernel for ``key``, building (and jitting) it on
    first use. ``build`` must close only over values captured by the key."""
    fn = _GLOBAL_KERNEL_CACHE.get(key)
    if fn is None:
        fn = tpu_jit(build())
        _GLOBAL_KERNEL_CACHE[key] = fn
    return fn


def shared_traces(key: tuple) -> dict:
    """Process-wide trace dict for an exec kernel, keyed by STRUCTURE
    (operator kind + bound expression keys + input schema). Exec instances
    are per-query; two queries with the same structure must share traces so
    a warm process never re-traces/re-compiles (VERDICT r1: per-instance jit
    caches made every fresh DataFrame recompile the whole pipeline)."""
    return _GLOBAL_KERNEL_CACHE.setdefault(key, {})


def clear_kernel_caches() -> int:
    """Drop every structurally-keyed kernel trace and compiled project
    (device-loss recovery, runtime/health.py): cached jitted callables
    hold executables and interned constants on the dead backend, so a
    reinitialized device must trace fresh. Returns entries dropped."""
    n = len(_GLOBAL_KERNEL_CACHE) + len(_GLOBAL_PROJECT_CACHE._cache)
    _GLOBAL_KERNEL_CACHE.clear()
    _GLOBAL_PROJECT_CACHE._cache.clear()
    return n


def compile_project(exprs: Sequence[Expression], table: DeviceTable):
    """Evaluate bound expressions over a device table, returning device
    columns. Compilation is cached globally."""
    return _GLOBAL_PROJECT_CACHE.get(exprs, table)(table)


def has_position_dependent(expr: "Expression") -> bool:
    """Does any node in the tree depend on physical row position? Used to
    force compaction before evaluating over a masked batch."""
    if getattr(expr, "position_dependent", False):
        return True
    return any(has_position_dependent(c) for c in expr.children)
