"""Input-chain fusion: inline Project/Filter chains into a consuming exec.

The reference collapses whole operator chains into one GPU kernel launch via
Spark's WholeStageCodegen boundaries + cuDF AST fusion; the XLA analog is
better — substitute the projection expressions into the consumer's
expression trees and evaluate filter predicates as weight masks inside the
consumer's single jitted program. XLA then fuses everything into one pass
over HBM: no intermediate materialization, no row-compaction scatters.

(reference: GpuHashAggregateExec boundInputReferences,
basicPhysicalOperators.scala GpuProjectExec/GpuFilterExec)
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from spark_rapids_tpu.ops.expr import Alias, BoundReference, Expression


def strip_alias(e: Expression) -> Expression:
    while isinstance(e, Alias):
        e = e.children[0]
    return e


def substitute(expr: Expression, mapping: Sequence[Expression]) -> Expression:
    """Replace every BoundReference(i) in ``expr`` with ``mapping[i]``
    (the projection that produced column i)."""
    if isinstance(expr, BoundReference):
        return mapping[expr.ordinal]
    if not expr.children:
        return expr
    return expr.with_children([substitute(c, mapping) for c in expr.children])


def peel_input_chain(child, exprs: List[Expression]):
    """Walk Project/Filter execs below ``child``, rewriting ``exprs`` to be
    bound against the base exec's schema and collecting filter predicates.

    Returns (base_exec, rewritten_exprs, predicates). Predicates are bound
    against the base schema; conjunction semantics (row kept iff every
    predicate is non-null true)."""
    from spark_rapids_tpu.execs.basic import TpuFilterExec, TpuProjectExec

    exprs = list(exprs)
    preds: List[Expression] = []
    cur = child
    while True:
        if isinstance(cur, TpuProjectExec):
            mapping = [strip_alias(e) for e in cur.exprs]
            exprs = [substitute(e, mapping) for e in exprs]
            preds = [substitute(p, mapping) for p in preds]
            cur = cur.children[0]
        elif isinstance(cur, TpuFilterExec):
            preds.append(cur.condition)
            cur = cur.children[0]
        else:
            return cur, exprs, preds
