"""FileCache — decoded-batch cache for file scans.

Reference: the FileCache subsystem (filecache/FileCache.scala) caches
remote file data/footers on local disk so repeated scans skip the slow
fetch. Here the slow layer is host DECODE (parse/convert to columns), so
the cache holds decoded HostTables keyed by (path, mtime, scan options),
LRU-bounded by ``spark.rapids.filecache.maxBytes``. Off by default like
the reference; decoded batches also warm the scan DEVICE cache upstream.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Optional

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import bool_conf, int_conf
from spark_rapids_tpu.lockorder import ordered_lock

FILECACHE_ENABLED = bool_conf(
    "spark.rapids.filecache.enabled", False,
    "Cache decoded file batches in host memory keyed by (path, mtime, "
    "scan options); repeated scans skip the decode (FileCache analog).")

FILECACHE_MAX_BYTES = int_conf(
    "spark.rapids.filecache.maxBytes", 1 << 30,
    "LRU budget for the decoded-batch file cache.")


class _FileCache:
    def __init__(self):
        self._lock = ordered_lock("io.filecache")
        self._entries: "OrderedDict[tuple, HostTable]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get_or_decode(self, path: str, options_key: tuple,
                      decode: Callable[[], HostTable],
                      max_bytes: int) -> HostTable:
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return decode()
        key = (os.path.abspath(path), mtime, options_key)
        with self._lock:
            got = self._entries.get(key)
            if got is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return got
            self.misses += 1
        table = decode()
        size = table.nbytes()
        if size > max_bytes:
            return table  # too big to cache
        with self._lock:
            if key not in self._entries:  # concurrent decode of same key
                self._entries[key] = table
                self._bytes += size
            while self._bytes > max_bytes and self._entries:
                _, old = self._entries.popitem(last=False)
                self._bytes -= old.nbytes()
        return table

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._bytes = 0


FILE_CACHE = _FileCache()
