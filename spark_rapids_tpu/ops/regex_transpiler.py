"""Java-regex -> Python-re transpiler with a strict reject guard.

Reference: RegexParser.scala (2,186 LoC) — spark-rapids treats regex
compatibility as a first-class problem: patterns are parsed and either
TRANSPILED to a semantically exact cudf pattern or REJECTED so the plan
falls back, never silently evaluated with divergent semantics. This module
is the same guard for Python `re`:

Java/Python divergences handled by transpilation (always compiled with
re.ASCII so remaining classes are ASCII like Java's default):

  \\d \\w \\s (and negations)  Java is ASCII-only; Python str patterns are
                            unicode -> expanded to explicit ASCII classes
  .                         Java excludes \\n \\r \\u0085 \\u2028 \\u2029;
                            Python excludes only \\n -> expanded class
  $                         Java matches before a FINAL line terminator
                            (incl. \\r, \\r\\n); Python only before \\n ->
                            lookahead expansion
  \\z / \\Z                   Java \\z == Python \\Z (absolute end); Java \\Z ->
                            the $ lookahead
  (?<name>...)              Java named group -> (?P<name>...)
  \\Q...\\E                   literal quoting -> re.escape'd text

REJECTED (raise RegexUnsupported -> expression tags CPU fallback with the
reason): possessive quantifiers (a*+), character-class intersection
([a-z&&[b]]), POSIX classes ([:alpha:]), \\p{...} properties, word
boundaries \\b \\B (Java's ASCII \\w definition cannot be expressed), \\G \\R
\\h \\H \\v \\V \\X, octal \\0nn, \\x{...}, inline flags (other than a single
leading (?s)), and anything Python's compiler itself refuses.
"""

from __future__ import annotations

import re

#: Java line terminators (Pattern: \n \r \u0085 \u2028 \u2029; . excludes
#: them all, $ matches before a final one)
_LINE_TERM = "\\n\\r\\u0085\\u2028\\u2029"

_DOT = f"[^{_LINE_TERM}]"
_DOLLAR = f"(?=(?:\\r\\n|[{_LINE_TERM}])?\\Z)"

_CLASS_EXPANSIONS = {
    "d": "[0-9]",
    "D": "[^0-9]",
    "w": "[a-zA-Z0-9_]",
    "W": "[^a-zA-Z0-9_]",
    "s": "[ \\t\\n\\x0b\\f\\r]",
    "S": "[^ \\t\\n\\x0b\\f\\r]",
}

_IN_CLASS_EXPANSIONS = {
    "d": "0-9",
    "w": "a-zA-Z0-9_",
    "s": " \\t\\n\\x0b\\f\\r",
}

#: escapes with identical semantics in both engines (passthrough)
_SAFE_ESCAPES = set("\\.[]{}()*+?^$|/-tnrfae" "0123456789" "xu")


class RegexUnsupported(Exception):
    """Pattern uses a construct whose Java semantics cannot be reproduced
    exactly with Python re — the expression must fall back."""


def transpile_java_regex(pattern: str) -> str:
    """Return a Python-re pattern (compile with re.ASCII) matching exactly
    like Java's Pattern (default flags), or raise RegexUnsupported."""
    out = []
    i = 0
    n = len(pattern)
    dotall = False
    if pattern.startswith("(?s)"):
        dotall = True
        out.append("(?s)")
        i = 4

    def reject(why):
        raise RegexUnsupported(f"regex {pattern!r}: {why}")

    while i < n:
        ch = pattern[i]
        if ch == "\\":
            if i + 1 >= n:
                reject("dangling backslash")
            nxt = pattern[i + 1]
            if nxt in _CLASS_EXPANSIONS:
                out.append(_CLASS_EXPANSIONS[nxt])
                i += 2
            elif nxt == "Q":
                end = pattern.find("\\E", i + 2)
                if end < 0:
                    reject("\\Q without \\E")
                out.append(re.escape(pattern[i + 2:end]))
                i = end + 2
            elif nxt == "z":
                out.append("\\Z")
                i += 2
            elif nxt == "Z":
                out.append(_DOLLAR)
                i += 2
            elif nxt == "A":
                out.append("\\A")
                i += 2
            elif nxt in ("b", "B", "G", "R", "h", "H", "v", "V", "X",
                         "p", "P", "k", "c"):
                reject(f"\\{nxt} has no exact Python equivalent")
            elif nxt == "0":
                reject("octal escapes differ between engines")
            elif nxt == "x" and i + 2 < n and pattern[i + 2] == "{":
                reject("\\x{...} is Java-only syntax")
            elif nxt in _SAFE_ESCAPES or not nxt.isalnum():
                out.append(pattern[i:i + 2])
                i += 2
            else:
                reject(f"escape \\{nxt} is not in the verified subset")
        elif ch == "[":
            cls, i = _transpile_class(pattern, i, reject)
            out.append(cls)
        elif ch == ".":
            out.append("." if dotall else _DOT)
            i += 1
        elif ch == "$":
            out.append(_DOLLAR)
            i += 1
        elif ch == "(":
            if pattern.startswith("(?", i) and not pattern.startswith("(?:", i):
                if pattern.startswith("(?<", i) and not (
                        pattern.startswith("(?<=", i)
                        or pattern.startswith("(?<!", i)):
                    out.append("(?P<")
                    i += 3
                elif (pattern.startswith("(?=", i)
                      or pattern.startswith("(?!", i)
                      or pattern.startswith("(?<=", i)
                      or pattern.startswith("(?<!", i)):
                    j = 4 if pattern.startswith("(?<", i) else 3
                    out.append(pattern[i:i + j])
                    i += j
                else:
                    reject("inline groups/flags beyond (?:...) "
                           "(?=/?!/?<=/?<!) and (?<name>) are unsupported")
            else:
                out.append(ch)
                i += 1
        elif ch in "*+?" and out and out[-1] and i + 1 < n \
                and pattern[i + 1] == "+":
            reject("possessive quantifiers are Java-only")
        else:
            out.append(ch)
            i += 1

    result = "".join(out)
    try:
        re.compile(result, re.ASCII)
    except re.error as e:
        reject(f"python re rejected the transpilation: {e}")
    return result


def _transpile_class(pattern: str, start: int, reject):
    """Transpile one [...] character class; returns (text, next_index)."""
    i = start + 1
    n = len(pattern)
    body = ["["]
    if i < n and pattern[i] == "^":
        body.append("^")
        i += 1
    if i < n and pattern[i] == "]":
        # Java allows a literal ] first; Python needs it escaped
        body.append("\\]")
        i += 1
    while i < n:
        ch = pattern[i]
        if ch == "]":
            body.append("]")
            return "".join(body), i + 1
        if ch == "&" and pattern.startswith("&&", i):
            reject("character-class intersection [..&&..] is Java-only")
        if ch == "[":
            if pattern.startswith("[:", i):
                reject("POSIX classes [:...:] are unsupported")
            reject("nested character classes are Java-only")
        if ch == "\\":
            if i + 1 >= n:
                reject("dangling backslash in class")
            nxt = pattern[i + 1]
            if nxt in _IN_CLASS_EXPANSIONS:
                body.append(_IN_CLASS_EXPANSIONS[nxt])
                i += 2
                continue
            if nxt in ("D", "W", "S"):
                reject(f"negated \\{nxt} inside a class cannot be expanded")
            if nxt in ("p", "P"):
                reject("\\p{...} properties are unsupported")
            if nxt == "0":
                reject("octal escapes differ between engines")
            body.append(pattern[i:i + 2])
            i += 2
            continue
        body.append(ch)
        i += 1
    reject("unterminated character class")


import functools


@functools.lru_cache(maxsize=1024)
def try_transpile(pattern: str):
    """(python_pattern, None) on success; (None, reason) on rejection.
    Cached: callers invoke this per dictionary entry / per row."""
    try:
        return transpile_java_regex(pattern), None
    except RegexUnsupported as e:
        return None, str(e)
