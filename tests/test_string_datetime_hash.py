"""String / datetime / hash expression tests vs the CPU oracle
(reference: string_test.py, date_time_test.py, hashing_test.py — SURVEY §4)."""

import datetime

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.ops.expr import col
from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
from tests.data_gen import (
    DateGen,
    IntGen,
    LongGen,
    StringGen,
    TimestampGen,
    gen_table,
)


def _s_table(n=400, seed=0):
    return gen_table({"s": StringGen(max_len=15), "v": LongGen()}, n, seed=seed)


STRING_FNS = [
    ("upper", lambda: F.upper("s")),
    ("lower", lambda: F.lower("s")),
    ("length", lambda: F.length("s")),
    ("bit_length", lambda: F.bit_length("s")),
    ("octet_length", lambda: F.octet_length("s")),
    ("ascii", lambda: F.ascii("s")),
    ("reverse", lambda: F.reverse("s")),
    ("initcap", lambda: F.initcap("s")),
    ("trim", lambda: F.trim("s")),
    ("ltrim", lambda: F.ltrim("s")),
    ("rtrim", lambda: F.rtrim("s")),
    ("substr_2_3", lambda: F.substring("s", 2, 3)),
    ("substr_neg", lambda: F.substring("s", -4, 2)),
    ("substr_0", lambda: F.substring("s", 0, 5)),
    ("repeat", lambda: F.repeat("s", 2)),
    ("replace", lambda: F.replace("s", "a", "XY")),
    ("lpad", lambda: F.lpad("s", 8, "*-")),
    ("rpad", lambda: F.rpad("s", 8, "*-")),
    ("substring_index", lambda: F.substring_index("s", "a", 1)),
    ("substring_index_neg", lambda: F.substring_index("s", "a", -1)),
    ("translate", lambda: F.translate("s", "abc", "XY")),
    ("concat_lit", lambda: F.concat(F.lit("pre_"), col("s"), F.lit("_post"))),
    ("contains", lambda: F.contains("s", "ab")),
    ("startswith", lambda: F.startswith("s", "A")),
    ("endswith", lambda: F.endswith("s", "z")),
    ("like", lambda: F.like("s", "%a_b%")),
    ("instr", lambda: F.instr("s", "ab")),
    ("locate", lambda: F.locate("a", "s", 2)),
    ("regexp_extract", lambda: F.regexp_extract("s", r"([A-Za-z]+)", 1)),
    ("regexp_replace", lambda: F.regexp_replace("s", r"[0-9]+", "#")),
]


@pytest.mark.parametrize("name,make", STRING_FNS, ids=[n for n, _ in STRING_FNS])
def test_string_functions(session, cpu_session, name, make):
    host = _s_table()
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).select(col("s"), make().alias("r")),
        session, cpu_session)


def test_string_fn_runs_on_tpu(session):
    host = _s_table(100)
    assert_runs_on_tpu(
        lambda s: s.create_dataframe(host).select(
            F.upper("s").alias("u"), F.length("s").alias("l"),
            F.like("s", "a%").alias("p")), session)


def test_string_fn_composes_with_filter_agg(session, cpu_session):
    host = _s_table(600, seed=3)
    assert_tpu_and_cpu_are_equal(
        lambda s: (s.create_dataframe(host)
                   .filter(F.length("s") > 5)
                   .group_by(F.substring("s", 1, 1).alias("first"))
                   .agg(F.count("s").alias("c"))),
        session, cpu_session)


def test_multicolumn_concat_falls_back(session):
    from spark_rapids_tpu.overrides import wrap_plan
    host = gen_table({"a": StringGen(), "b": StringGen()}, 50)
    df = session.create_dataframe(host).select(
        F.concat(col("a"), col("b")).alias("ab"))
    meta = wrap_plan(df.plan, session.conf)
    assert not meta.can_run_on_tpu
    # still correct through CPU
    rows = df.collect()
    assert len(rows) == 50


def test_empty_and_unicode_strings(session, cpu_session):
    host = HostTable.from_pydict(
        {"s": ["", "héllo wörld", "日本語", None, "  pad  ", "ABC123xyz"]})
    for name, make in [("upper", lambda: F.upper("s")),
                       ("len", lambda: F.length("s")),
                       ("octet", lambda: F.octet_length("s")),
                       ("rev", lambda: F.reverse("s")),
                       ("trim", lambda: F.trim("s"))]:
        assert_tpu_and_cpu_are_equal(
            lambda s: s.create_dataframe(host).select(make().alias("r")),
            session, cpu_session)


# -- datetime ---------------------------------------------------------------

def _d_table(n=500, seed=1):
    return gen_table({"d": DateGen(), "ts": TimestampGen(),
                      "n": IntGen(min_val=-1000, max_val=1000, null_prob=0.0)},
                     n, seed=seed)


DATE_FNS = [
    ("year", lambda: F.year("d")),
    ("month", lambda: F.month("d")),
    ("dayofmonth", lambda: F.dayofmonth("d")),
    ("dayofweek", lambda: F.dayofweek("d")),
    ("weekday", lambda: F.weekday("d")),
    ("dayofyear", lambda: F.dayofyear("d")),
    ("quarter", lambda: F.quarter("d")),
    ("last_day", lambda: F.last_day("d")),
    ("date_add", lambda: F.date_add("d", col("n"))),
    ("date_sub", lambda: F.date_sub("d", col("n"))),
    ("add_months", lambda: F.add_months("d", col("n"))),
    ("hour", lambda: F.hour("ts")),
    ("minute", lambda: F.minute("ts")),
    ("second", lambda: F.second("ts")),
    ("to_unix", lambda: F.to_unix_timestamp("ts")),
    ("to_date", lambda: F.to_date("ts")),
]


@pytest.mark.parametrize("name,make", DATE_FNS, ids=[n for n, _ in DATE_FNS])
def test_datetime_functions(session, cpu_session, name, make):
    host = _d_table()
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).select(make().alias("r")),
        session, cpu_session)


def test_civil_calendar_against_python_datetime(session):
    """Device calendar math vs python datetime over known dates."""
    dates = [datetime.date(1970, 1, 1), datetime.date(2000, 2, 29),
             datetime.date(1900, 3, 1), datetime.date(2024, 12, 31),
             datetime.date(1, 1, 1), datetime.date(9999, 12, 31),
             datetime.date(1969, 12, 31)]
    host = HostTable.from_pydict({"d": dates}, dtypes={"d": T.DATE})
    rows = session.create_dataframe(host).select(
        F.year("d").alias("y"), F.month("d").alias("m"),
        F.dayofmonth("d").alias("dd"), F.dayofweek("d").alias("dw"),
        F.dayofyear("d").alias("dy")).collect()
    for date, (y, m, dd, dw, dy) in zip(dates, rows):
        assert (y, m, dd) == (date.year, date.month, date.day)
        assert dw == date.isoweekday() % 7 + 1
        assert dy == date.timetuple().tm_yday


def test_datediff_and_roundtrips(session, cpu_session):
    host = _d_table(300, seed=5)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).select(
            F.datediff(F.date_add("d", col("n")), col("d")).alias("dd"),
            F.timestamp_seconds(F.to_unix_timestamp("ts")).alias("trunc_s")),
        session, cpu_session)


# -- hash expressions -------------------------------------------------------

def test_xxhash64_spark_documented_vector():
    from spark_rapids_tpu.ops.hashfns import xxhash64_host
    # Spark SQL docs: SELECT xxhash64('Spark', array(123), 2)
    from spark_rapids_tpu.ops.hashfns import _np_xx_bytes, _np_xx_int
    h = _np_xx_bytes(b"Spark", 42)
    h = _np_xx_int(123, h)
    h = _np_xx_int(2, h)
    assert int(np.uint64(h).view(np.int64)) == 5602566077635097486


@pytest.mark.parametrize("fn", ["hash", "xxhash64"])
def test_hash_exprs_device_matches_host(session, cpu_session, fn):
    host = gen_table({"i": IntGen(), "l": LongGen(), "s": StringGen(max_len=40),
                      "d": DateGen()}, 400, seed=7)
    make = getattr(F, fn)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).select(
            make(col("i"), col("l"), col("s"), col("d")).alias("h")),
        session, cpu_session)


def test_hash_runs_on_tpu(session):
    host = gen_table({"i": IntGen(), "s": StringGen()}, 100)
    assert_runs_on_tpu(
        lambda s: s.create_dataframe(host).select(
            F.hash(col("i"), col("s")).alias("h"),
            F.xxhash64(col("i"), col("s")).alias("x")), session)


def test_xxhash64_long_strings(session, cpu_session):
    """Strings past the 32-byte stripe threshold exercise the full XXH64."""
    host = HostTable.from_pydict({"s": [
        "x" * 100, "abcdefgh" * 5, "", "short", None,
        "0123456789abcdefghijklmnopqrstuv",  # exactly 32
        "0123456789abcdefghijklmnopqrstuvw",  # 33
    ]})
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host).select(F.xxhash64(col("s")).alias("h")),
        session, cpu_session)
