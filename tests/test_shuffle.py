"""Shuffle layer tests: murmur3 exactness, partitioners, serializer,
multithreaded shuffle manager, exchange exec, ICI all-to-all exchange
(reference: RapidsShuffleClientSuite-style in-process protocol tests +
repart_test.py — SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceTable, HostTable
from spark_rapids_tpu.ops.expr import col
from spark_rapids_tpu.shuffle.hashing import (
    murmur3_hash_device,
    murmur3_hash_host,
    string_dict_bytes,
)
from spark_rapids_tpu.shuffle.partitioning import (
    HashPartitioner,
    RangePartitioner,
    RoundRobinPartitioner,
    SinglePartitioner,
    split_by_partition,
)
from spark_rapids_tpu.shuffle.serializer import pack_table, unpack_table
from tests.data_gen import (
    DoubleGen,
    IntGen,
    LongGen,
    StringGen,
    all_basic_gens,
    gen_table,
)

def test_murmur3_spark_documented_vector():
    """The one authoritative offline oracle: the Spark SQL function docs'
    example `SELECT hash('Spark', array(123), 2)` == -1321691492, which
    exercises string bytes + int chaining + seed threading."""
    from spark_rapids_tpu.shuffle.hashing import _np_hash_bytes, _np_hash_int
    h = _np_hash_bytes(b"Spark", np.uint32(42))
    h = _np_hash_int(123, h)
    h = _np_hash_int(2, h)
    assert int(np.int32(h)) == -1321691492


# Regression vectors produced by the doc-validated implementation (pin the
# algorithm; cross-checked against CPU Spark when the oracle cluster runs).
SPARK_HASH_VECTORS = [
    (0, T.INT, 933211791),
    (1, T.INT, -559580957),
    (42, T.INT, 29417773),
    (-1, T.INT, -1604776387),
    (0, T.LONG, -1670924195),
    (1, T.LONG, -1712319331),
    (42, T.LONG, 1316951768),
    (True, T.BOOLEAN, -559580957),
    (False, T.BOOLEAN, 933211791),
    ("", T.STRING, 142593372),
    ("abc", T.STRING, 1322437556),
    ("hello", T.STRING, -1008564952),
    (1.0, T.DOUBLE, -460888942),
    (0.0, T.DOUBLE, -1670924195),
    (1.5, T.FLOAT, -221251528),
]


@pytest.mark.parametrize("value,dt,expected", SPARK_HASH_VECTORS,
                         ids=[f"{d.simple_string()}_{v}" for v, d, e in
                              SPARK_HASH_VECTORS])
def test_murmur3_spark_vectors_host(value, dt, expected):
    got = murmur3_hash_host([(value, True, dt)])
    assert got == expected, f"hash({value}:{dt}) = {got}, want {expected}"


def test_murmur3_device_matches_host():
    host = gen_table({"i": IntGen(), "l": LongGen(), "d": DoubleGen(),
                      "s": StringGen(max_len=17)}, 500, seed=3)
    dt = DeviceTable.from_host(host)
    sb = {}
    cols = []
    for i, c in enumerate(dt.columns):
        cols.append((c.data, c.validity, c.dtype))
        if isinstance(c.dtype, T.StringType):
            mat, lens = string_dict_bytes(c.dictionary)
            sb[i] = (jnp.asarray(mat), jnp.asarray(lens))
    dev = np.asarray(jax.jit(
        lambda: murmur3_hash_device(cols, string_bytes=sb))())[:500]

    rows = list(zip(*[c.to_pylist() for c in host.columns]))
    for r in range(500):
        vals = [(rows[r][j], rows[r][j] is not None, host.columns[j].dtype)
                for j in range(4)]
        want = murmur3_hash_host(vals)
        assert int(dev[r]) == want, f"row {r}: {vals}"


def test_null_hash_passes_seed_through():
    assert murmur3_hash_host([(None, False, T.INT)]) == 42
    got = murmur3_hash_host([(None, False, T.INT), (1, True, T.INT)])
    assert got == murmur3_hash_host([(1, True, T.INT)])


# -- partitioners -----------------------------------------------------------

def _id_table(n=1000, seed=0):
    return gen_table({"k": IntGen(null_prob=0.05), "s": StringGen(),
                      "v": LongGen()}, n, seed=seed)


def test_hash_partition_split_roundtrip():
    host = _id_table()
    dt = DeviceTable.from_host(host)
    parts = split_by_partition(dt, HashPartitioner([col("k").bind(host.schema())], 8))
    assert sum(p.num_rows for p in parts) == 1000
    merged = HostTable.concat([p for p in parts if p.num_rows])
    a = sorted(map(str, zip(*[c.to_pylist() for c in merged.columns])))
    b = sorted(map(str, zip(*[c.to_pylist() for c in host.columns])))
    assert a == b


def test_hash_partition_deterministic_spark_pmod():
    """Partition id must equal pmod(spark_hash(k), n) exactly."""
    host = HostTable.from_pydict({"k": [0, 1, 42, None, -7]})
    dt = DeviceTable.from_host(host)
    p = HashPartitioner([col("k").bind(host.schema())], 4)
    pids = np.asarray(jax.device_get(p.partition_ids(dt)))[:5]
    for i, v in enumerate([0, 1, 42, None, -7]):
        h = murmur3_hash_host([(v, v is not None, T.INT)])
        want = ((h % 4) + 4) % 4
        assert pids[i] == want


def test_round_robin_and_single():
    host = _id_table(100)
    dt = DeviceTable.from_host(host)
    parts = split_by_partition(dt, RoundRobinPartitioner(3))
    assert sum(p.num_rows for p in parts) == 100
    assert max(p.num_rows for p in parts) - min(p.num_rows for p in parts) <= 1
    single = split_by_partition(dt, SinglePartitioner())
    assert len(single) == 1 and single[0].num_rows == 100


@pytest.mark.parametrize("keycol", ["k", "s"])
def test_range_partition_orders_partitions(keycol):
    host = _id_table(2000, seed=5)
    dt = DeviceTable.from_host(host)
    schema = host.schema()
    rp = RangePartitioner([col(keycol).bind(schema)], 4)
    parts = split_by_partition(dt, rp)
    assert sum(p.num_rows for p in parts) == 2000
    # every value in partition p must be <= every value in partition p+1
    maxes, mins = [], []
    for p in parts:
        vals = [v for v in p.column(keycol).to_pylist() if v is not None]
        if vals:
            maxes.append(max(vals))
            mins.append(min(vals))
    for a, b in zip(maxes, mins[1:]):
        assert a <= b


# -- serializer -------------------------------------------------------------

def test_pack_unpack_all_types():
    gens = {f"c{i}": g for i, g in enumerate(all_basic_gens)}
    host = gen_table(gens, 700, seed=9)
    buf = pack_table(host)
    back, consumed = unpack_table(buf)
    assert consumed == len(buf)
    assert back.schema() == host.schema()
    assert back.to_pydict() == host.to_pydict()


def test_pack_unpack_empty_and_concat_stream():
    t1 = HostTable.from_pydict({"a": [1, 2], "s": ["x", None]})
    t2 = HostTable.from_pydict({"a": [], "s": []},
                               dtypes={"a": T.INT, "s": T.STRING})
    buf = pack_table(t1) + pack_table(t2) + pack_table(t1)
    pos = 0
    tables = []
    while pos < len(buf):
        t, used = unpack_table(buf, pos)
        tables.append(t)
        pos += used
    assert len(tables) == 3
    assert tables[0].to_pydict() == t1.to_pydict()
    assert tables[1].num_rows == 0


def test_pack_decimal():
    t = HostTable.from_pydict({"d": [1234, None, -5678]},
                              dtypes={"d": T.DecimalType(9, 2)})
    back, _ = unpack_table(pack_table(t))
    assert back.columns[0].dtype == T.DecimalType(9, 2)
    assert back.to_pydict() == t.to_pydict()


# -- shuffle manager --------------------------------------------------------

def test_shuffle_manager_write_read(session):
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    mgr = ShuffleManager(session.conf)
    host = _id_table(600, seed=2)
    dt = DeviceTable.from_host(host)
    partitioner = HashPartitioner([col("k").bind(host.schema())], 5)

    h = mgr.new_shuffle(5)
    # two map outputs (two batches)
    h.write_partitions(split_by_partition(dt, partitioner))
    h.write_partitions(split_by_partition(dt, partitioner))
    reader = mgr.reader(h)
    total = 0
    for p in range(5):
        for t in reader.read_partition(p):
            total += t.num_rows
    assert total == 1200
    mgr.remove_shuffle(h)


def test_shuffle_manager_compression(session):
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    conf = session.conf.set("spark.rapids.shuffle.compression.codec", "zstd")
    mgr = ShuffleManager(conf)
    host = _id_table(500)
    dt = DeviceTable.from_host(host)
    h = mgr.new_shuffle(2)
    h.write_partitions(split_by_partition(
        dt, HashPartitioner([col("k").bind(host.schema())], 2)))
    rows = sum(t.num_rows for p in range(2)
               for t in mgr.reader(h).read_partition(p))
    assert rows == 500
    mgr.remove_shuffle(h)


# -- exchange exec through the engine ---------------------------------------

def test_repartition_roundtrip(session, cpu_session):
    from tests.asserts import assert_tpu_and_cpu_are_equal
    host = _id_table(1500, seed=7)
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(host, num_batches=3).repartition(4, "k"),
        session, cpu_session)


def test_repartition_then_aggregate(session, cpu_session):
    from spark_rapids_tpu import functions as F
    from tests.asserts import assert_tpu_and_cpu_are_equal
    host = _id_table(2000, seed=8)
    assert_tpu_and_cpu_are_equal(
        lambda s: (s.create_dataframe(host, num_batches=4)
                   .repartition(3, "k")
                   .group_by("k").agg(F.sum("v").alias("sv"))),
        session, cpu_session)


def test_exchange_runs_on_tpu(session):
    from tests.asserts import assert_runs_on_tpu
    host = _id_table(300)
    assert_runs_on_tpu(
        lambda s: s.create_dataframe(host).repartition(4, "k"), session)


# -- ICI all-to-all exchange over the 8-device mesh -------------------------

def test_mesh_hash_exchange_partitions_by_murmur3():
    from jax.sharding import Mesh
    from spark_rapids_tpu.parallel import mesh_hash_exchange

    ndev = 8
    devices = np.array(jax.devices()[:ndev])
    mesh = Mesh(devices, ("data",))
    n = 1024  # 128 rows per device
    rng = np.random.default_rng(0)
    k = rng.integers(-1000, 1000, n).astype(np.int32)
    v = rng.integers(0, 10**9, n).astype(np.int64)
    kv = np.ones(n, dtype=np.bool_)

    run = mesh_hash_exchange(mesh, [T.INT, T.LONG], key_idx=[0])
    (out_k, out_v), (ov_k, ov_v), live = (
        lambda o: (o[0], o[1], o[2]))(run([jnp.asarray(k), jnp.asarray(v)],
                                          [jnp.asarray(kv), jnp.asarray(kv)]))
    live = np.asarray(jax.device_get(live))
    out_k = np.asarray(jax.device_get(out_k))
    out_v = np.asarray(jax.device_get(out_v))

    # every input row arrives exactly once
    got = sorted(zip(out_k[live].tolist(), out_v[live].tolist()))
    want = sorted(zip(k.tolist(), v.tolist()))
    assert got == want

    # and lands on the device matching pmod(murmur3(k), ndev)
    per_dev = len(out_k) // ndev
    for r in np.nonzero(live)[0]:
        dev = r // per_dev
        h = murmur3_hash_host([(int(out_k[r]), True, T.INT)])
        assert ((h % ndev) + ndev) % ndev == dev


def test_range_partition_string_bounds_consistent_across_batches():
    """A bound value absent from one batch's dictionary must not split
    equal keys across partitions (ADVICE r1: inexact bound codes)."""
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import HostColumn, HostTable
    from spark_rapids_tpu.columnar.table import DeviceTable
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.shuffle.partitioning import RangePartitioner

    # batch1 lacks "banana" (a likely sampled bound from batch2)
    b1 = DeviceTable.from_host(HostTable(["s"], [HostColumn(
        T.STRING, np.array(["apple", "cherry", "apple", "date"] * 30,
                           dtype=object))]))
    b2 = DeviceTable.from_host(HostTable(["s"], [HostColumn(
        T.STRING, np.array(["banana", "cherry", "banana", "elder"] * 30,
                           dtype=object))]))

    parter = RangePartitioner([col("s").bind([("s", T.STRING)])], 3,
                              samples_per_partition=40)
    parter.compute_bounds_multi([b1, b2])

    mapping = {}
    for b in (b1, b2):
        pids = np.asarray(parter.partition_ids(b))[:b.num_rows]
        vals = b.to_host().columns[0].data
        for v, p in zip(vals, pids):
            assert mapping.setdefault(v, int(p)) == int(p), \
                f"{v!r} landed in partitions {mapping[v]} and {int(p)}"
    # ordering invariant: lexicographically larger values never map to a
    # smaller partition
    items = sorted(mapping.items())
    pids_in_order = [p for _, p in items]
    assert pids_in_order == sorted(pids_in_order), items


def test_aqe_partition_coalescing(session, cpu_session):
    """Small adjacent shuffle partitions merge at read time (AQE analog);
    results unchanged, far fewer output batches."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.plan import from_host_table
    from tests.data_gen import IntGen, gen_table

    from spark_rapids_tpu.session import TpuSession
    t = gen_table({"k": IntGen(min_val=0, max_val=40), "v": IntGen()}, 400, 5)

    # default (ON since round 5 — AQE coalescing from measured sizes):
    # undersized partitions merge into a handful of batches
    df = from_host_table(t, session).repartition(64, "k")
    executable, _ = apply_overrides(df.plan, session.conf)
    default_batches = list(executable.execute_cpu())
    assert sum(b.num_rows for b in default_batches) == 400
    assert len(default_batches) <= 4

    off = TpuSession({
        "spark.rapids.sql.adaptive.coalescePartitions.enabled": "false"})
    df2 = from_host_table(t, off).repartition(64, "k")
    ex2, _ = apply_overrides(df2.plan, off.conf)
    batches = list(ex2.execute_cpu())
    # one batch per non-empty partition when disabled
    assert len(default_batches) < len(batches)
    assert sum(b.num_rows for b in batches) == 400

    # correctness through a grouped aggregate with coalescing ON (default)
    from tests.asserts import assert_tpu_and_cpu_are_equal
    assert_tpu_and_cpu_are_equal(
        lambda s: from_host_table(t, s)
        .repartition(64, "k")
        .group_by("k").agg(F.count().alias("c"), F.sum(col("v")).alias("s")),
        session, cpu_session)


def test_codec_resolution_and_roundtrip(session):
    """lz4 resolves to the native C++ block codec, zstd to zstandard; the
    resolved name must round-trip the data it claims to describe."""
    from spark_rapids_tpu.shuffle.manager import (
        _compress, _decompress, resolve_codec)
    import numpy as np
    payload = np.arange(10000, dtype=np.int64).tobytes() + b"tail" * 321
    for requested in ("none", "zlib", "lz4", "zstd"):
        resolved = resolve_codec(requested)
        blob = _compress(resolved, payload)
        assert _decompress(resolved, blob) == payload
        if requested == "none":
            assert resolved == "none" and blob == payload
        else:
            assert len(blob) < len(payload)


def test_lz4_resolves_native(session):
    from spark_rapids_tpu.native import lz4_available
    from spark_rapids_tpu.shuffle.manager import resolve_codec
    if lz4_available():
        assert resolve_codec("lz4") == "lz4"
    else:
        assert resolve_codec("lz4") == "zlib"


def test_shuffle_manager_lz4(session):
    from spark_rapids_tpu.shuffle.manager import ShuffleManager
    conf = session.conf.set("spark.rapids.shuffle.compression.codec", "lz4")
    mgr = ShuffleManager(conf)
    host = _id_table(500)
    dt = DeviceTable.from_host(host)
    h = mgr.new_shuffle(2)
    h.write_partitions(split_by_partition(
        dt, HashPartitioner([col("k").bind(host.schema())], 2)))
    rows = sum(t.num_rows for p in range(2)
               for t in mgr.reader(h).read_partition(p))
    assert rows == 500
    mgr.remove_shuffle(h)


def test_local_device_split_repartition(session, cpu_session):
    """Single-process repartition takes the on-device masked split
    (round-4: no shuffle-manager round trip) with exact results."""
    from spark_rapids_tpu.functions import count
    from tests.data_gen import IntGen, gen_table
    from spark_rapids_tpu.plan import from_host_table
    t = gen_table({"k": IntGen(min_val=0, max_val=9), "v": IntGen()}, 500, 3)
    q = lambda s: sorted(
        from_host_table(t, s).repartition(4, "k").group_by("k")
        .agg(count("v").alias("c")).collect(), key=repr)
    got, want = q(session), q(cpu_session)
    assert got == want
    assert "localSplitParts" in session.last_metrics()


def test_local_device_split_disabled_by_conf():
    from tests.data_gen import IntGen, gen_table
    from spark_rapids_tpu.plan import from_host_table
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.shuffle.localDeviceSplit.enabled": "false"})
    t = gen_table({"k": IntGen(min_val=0, max_val=9)}, 200, 2)
    _ = from_host_table(t, s).repartition(4, "k").collect()
    m = s.last_metrics()
    assert "localSplitParts" not in m and "shuffle" in m.lower()


# -- AQE from measured map-output stats (default-on; VERDICT r4 #7) ----------

def _skewed_df(s, n=4000, nparts=16):
    import numpy as np
    rng = np.random.default_rng(0)
    k = rng.integers(0, nparts * 4, n).astype(np.int64)
    k[: n * 9 // 10] = 7  # one hot key owns 90% of rows
    return s.create_dataframe(
        {"k": k, "v": rng.integers(-100, 100, n).astype(np.int64)})


def test_aqe_coalescing_on_by_default_with_skew_stats(cpu_session):
    """Skewed shuffle through the HOST path: measured per-partition
    map-output sizes surface as stats, undersized partitions coalesce
    (default ON), the skewed partition is counted."""
    import numpy as np
    from spark_rapids_tpu.session import TpuSession
    # force the host shuffle (disable the device split) so the measured
    # map-output stats path runs
    s = TpuSession({"spark.rapids.shuffle.localDeviceSplit.enabled":
                    "false",
                    "spark.rapids.sql.batchSizeBytes": "16384"})
    got = sorted(_skewed_df(s).repartition(16, "k").collect(), key=repr)
    want = sorted(_skewed_df(cpu_session).repartition(16, "k").collect(),
                  key=repr)
    assert got == want
    m = s.last_metrics()
    assert "mapOutputBytesMax" in m, m
    assert "skewedPartitions" in m, m
    assert "aqeCoalescedPartitions" in m, m


def test_aqe_coalescing_can_be_disabled(cpu_session):
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.shuffle.localDeviceSplit.enabled":
                    "false",
                    "spark.rapids.sql.adaptive.coalescePartitions.enabled":
                    "false"})
    got = sorted(_skewed_df(s).repartition(8, "k").collect(), key=repr)
    want = sorted(_skewed_df(cpu_session).repartition(8, "k").collect(),
                  key=repr)
    assert got == want
    assert "aqeCoalescedPartitions" not in s.last_metrics()


def test_aqe_skewed_join_runtime_shape(cpu_session):
    """Skewed JOIN replanned from MEASURED sizes: a build side with no
    static estimate measures small at runtime -> broadcast shape; the
    same query with a large measured build keeps the sub-partitioned
    shuffled shape. Both decisions visible in metrics (reference:
    GpuCustomShuffleReaderExec / DynamicJoinSelection)."""
    import numpy as np
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.execs.broadcast import TpuAdaptiveBuildExec
    from spark_rapids_tpu.overrides.rules import apply_overrides
    from spark_rapids_tpu.ops.expr import col
    from spark_rapids_tpu.plan import nodes as P
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession()
    rng = np.random.default_rng(1)
    probe = HostTable.from_pydict(
        {"k": rng.integers(0, 30, 3000).astype(np.int64),
         "v": rng.standard_normal(3000)})

    def run(build_rows):
        build = HostTable.from_pydict(
            {"k": (np.arange(build_rows, dtype=np.int64) % 30),
             "w": np.arange(build_rows, dtype=np.int64)})
        scan = P.LocalScan([build])
        scan.estimate_bytes = lambda: None  # static planner can't prove
        join = P.Join(P.LocalScan([probe]), scan, "leftsemi",
                      [col("k")], [col("k")])
        ex, _ = apply_overrides(join, s.conf)

        def find(e):
            if isinstance(e, TpuAdaptiveBuildExec):
                return e
            for c in getattr(e, "children", ()):
                r = find(c)
                if r is not None:
                    return r
            for a in ("source", "tpu_exec"):
                nxt = getattr(e, a, None)
                if nxt is not None:
                    r = find(nxt)
                    if r is not None:
                        return r
            return None

        batches = list(ex.execute_cpu())
        ab = find(ex)
        assert ab is not None
        return ab.converted, HostTable.concat(batches).num_rows

    converted_small, n_small = run(30)
    assert converted_small is True  # runtime-measured -> broadcast shape
    big_session = TpuSession(
        {"spark.rapids.sql.broadcastSizeBytes": "64"})
    s = big_session
    converted_big, n_big = run(100000)
    assert converted_big is False  # stays the shuffled/sub-partitioned shape
    assert n_small == n_big  # same semantics either shape
