"""Flagship workloads (the reference's benchmark targets: TPC-H/TPC-DS-style
query pipelines, ScaleTest queries, mortgage ETL — SURVEY.md §6).

These are the "models" of a SQL engine: end-to-end query pipelines used for
benchmarking, the driver's compile checks, and multi-chip dry runs."""

from spark_rapids_tpu.models.tpch import lineitem_table, q1_dataframe, q1_kernel  # noqa: F401
