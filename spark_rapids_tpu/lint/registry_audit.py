"""Registry auditor (reference: the supported_ops.md generator contract —
docs, tag functions and registries must agree; round-5 VERDICT flagged
exactly this class of drift).

Cross-checks, with one Diagnostic per disagreement (RA-* rules):

* ops/* expression classes carrying a device kernel against the
  overrides ``_EXPR_SIGS`` registrations (unregistered = silently CPU);
* ``_EXPR_CHECKS`` per-parameter signatures against constructor arity;
* per-op kill-switch conf keys against the rule registries;
* device-supported aggregates against the SQL function registry;
* the committed SUPPORTED_OPS.md / CONFIGS.md against their generators.
"""

from __future__ import annotations

import importlib
import inspect
import os
import pkgutil
from typing import List, Optional

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make


def _repo_root(repo_root: Optional[str]) -> str:
    if repo_root:
        return repo_root
    import spark_rapids_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(spark_rapids_tpu.__file__)))


def _import_full_package() -> None:
    """Import every submodule so dynamically-registered rules/confs (file
    formats, delta, profiler, filecache...) are present — the same walk
    conf.generate_docs performs."""
    import spark_rapids_tpu
    for m in pkgutil.walk_packages(spark_rapids_tpu.__path__,
                                   "spark_rapids_tpu."):
        try:
            importlib.import_module(m.name)
        except Exception:
            pass  # optional backends (pyarrow etc.) may be absent


#: ops modules whose Expression subclasses evaluate through a DIFFERENT
#: support registry than _EXPR_SIGS (window functions gate through
#: execs.window.device_window_supported; aggregates register as classes
#: via DEVICE_SUPPORTED_AGGS — both audited separately below)
_NON_SIG_MODULES = ("spark_rapids_tpu.ops.window",)

#: classes that are never evaluated as row expressions, so an _EXPR_SIGS
#: entry would be meaningless: generator markers are consumed by the
#: Generate plan node (tagged by _tag_generate), and the HOF lambda
#: plumbing is rebound into element space by its enclosing function
_NON_EXPR_EVALUATED = {
    "Explode", "ExplodeOuter", "PosExplode", "PosExplodeOuter",
    "LambdaFunction", "NamedLambdaVariable",
}


def _audit_unregistered(diags: List[Diagnostic]) -> None:
    from spark_rapids_tpu.ops.expr import Expression
    from spark_rapids_tpu.overrides import rules as R
    from spark_rapids_tpu.overrides.typesig import lookup_mro
    R._build_expr_sigs()
    import spark_rapids_tpu.ops as ops_pkg
    for m in pkgutil.iter_modules(ops_pkg.__path__, "spark_rapids_tpu.ops."):
        if m.name in _NON_SIG_MODULES:
            continue
        try:
            mod = importlib.import_module(m.name)
        except Exception:
            continue
        for name in dir(mod):
            obj = getattr(mod, name)
            if not (isinstance(obj, type) and issubclass(obj, Expression)
                    and not name.startswith("_")
                    and obj.__module__ == mod.__name__
                    and "_is_expr_base" not in vars(obj)):
                continue
            has_dev = ("eval_dev" in {k for kls in obj.__mro__
                                      for k in vars(kls)}
                       and getattr(obj, "eval_dev", None)
                       is not Expression.eval_dev)
            if name in _NON_EXPR_EVALUATED:
                continue
            if has_dev and lookup_mro(R._EXPR_SIGS, obj) is None:
                diags.append(make(
                    "RA-UNREGISTERED", f"{m.name}.{name}",
                    "expression has a device kernel (eval_dev) but no "
                    "_EXPR_SIGS registration — it silently falls back "
                    "to CPU"))


def _audit_param_arity(diags: List[Diagnostic]) -> None:
    from spark_rapids_tpu.overrides import rules as R
    R._build_expr_sigs()
    for cls, checks in R._EXPR_CHECKS.items():
        try:
            sig = inspect.signature(cls.__init__)
        except (TypeError, ValueError):
            continue
        params = [p for n, p in sig.parameters.items() if n != "self"]
        if any(p.kind == inspect.Parameter.VAR_POSITIONAL for p in params):
            continue  # *args constructors accept any arity
        max_args = len([p for p in params if p.kind in (
            inspect.Parameter.POSITIONAL_ONLY,
            inspect.Parameter.POSITIONAL_OR_KEYWORD)])
        if len(checks.param_sigs) > max_args:
            diags.append(make(
                "RA-PARAM-ARITY",
                f"{cls.__module__}.{cls.__name__}",
                f"ExprChecks declares {len(checks.param_sigs)} parameter "
                f"signatures but the constructor takes at most "
                f"{max_args} positional arguments"))


#: expression kill switches registered outside the sig registries: Hive
#: UDF wrappers tag per-class fallback through _tag_python_udf, not
#: through _EXPR_SIGS (hive_udf.py registers these two at import)
_KNOWN_NON_SIG_SWITCHES = {"HiveSimpleUDF", "HiveGenericUDF"}


def _audit_kill_switches(diags: List[Diagnostic]) -> None:
    from spark_rapids_tpu import conf as C
    from spark_rapids_tpu.overrides import rules as R
    R._build_expr_sigs()
    exec_names = {cls.__name__ for cls in R._EXEC_RULES}
    expr_names = {cls.__name__ for cls in R._EXPR_SIGS}
    for key in C.registry():
        parts = key.split(".")
        if len(parts) != 5 or parts[:3] != ["spark", "rapids", "sql"]:
            continue
        kind, name = parts[3], parts[4]
        if kind == "exec" and name not in exec_names:
            diags.append(make(
                "RA-KILL-SWITCH", key,
                f"kill switch names exec {name!r} but no exec rule is "
                "registered under that class"))
        elif kind == "expression" and name not in expr_names \
                and name not in _KNOWN_NON_SIG_SWITCHES:
            diags.append(make(
                "RA-KILL-SWITCH", key,
                f"kill switch names expression {name!r} but no "
                "expression signature is registered under that class"))


#: device aggregate class -> the SQL builtin name users reach it by;
#: RA-SQL-EXPOSURE fails when a DEVICE_SUPPORTED_AGGS class is missing
#: here or its name is missing from the builtin table
_AGG_SQL_NAMES = {
    "Sum": "sum", "Min": "min", "Max": "max", "Count": "count",
    "Average": "avg", "First": "first", "Last": "last",
    "StddevPop": "stddev_pop", "StddevSamp": "stddev_samp",
    "VariancePop": "var_pop", "VarianceSamp": "var_samp",
    "CollectList": "collect_list", "CollectSet": "collect_set",
    "Percentile": "percentile",
}


def _audit_sql_exposure(diags: List[Diagnostic]) -> None:
    from spark_rapids_tpu.execs.aggregate import DEVICE_SUPPORTED_AGGS
    from spark_rapids_tpu.sql import registry as sql_registry
    try:
        table_probe = sql_registry.builtin("sum")
    except Exception as exc:
        diags.append(make(
            "RA-SQL-EXPOSURE", "sql.registry",
            f"builtin function table fails to build: {exc!r}"))
        return
    if table_probe is None:
        diags.append(make("RA-SQL-EXPOSURE", "sql.registry.sum",
                          "core aggregate 'sum' missing from builtins"))
    for cls in DEVICE_SUPPORTED_AGGS:
        sql_name = _AGG_SQL_NAMES.get(cls.__name__)
        where = f"sql.registry.{cls.__name__}"
        if sql_name is None:
            diags.append(make(
                "RA-SQL-EXPOSURE", where,
                f"device aggregate {cls.__name__} has no known SQL "
                "name (add it to the auditor map AND the SQL registry)"))
        elif sql_registry.builtin(sql_name) is None:
            diags.append(make(
                "RA-SQL-EXPOSURE", where,
                f"device aggregate {cls.__name__} is not callable from "
                f"SQL (builtin {sql_name!r} missing)"))


def _audit_doc_drift(diags: List[Diagnostic], root: str) -> None:
    from spark_rapids_tpu.conf import generate_docs
    from spark_rapids_tpu.overrides.docs import generate_supported_ops
    from spark_rapids_tpu.lockorder import generate_locks_md
    for fname, gen, rule in (
            ("SUPPORTED_OPS.md", generate_supported_ops,
             "RA-DOC-DRIFT-OPS"),
            ("CONFIGS.md", generate_docs, "RA-DOC-DRIFT-CONFIGS"),
            ("LOCKS.md", generate_locks_md, "RA-DOC-DRIFT-LOCKS")):
        path = os.path.join(root, fname)
        if not os.path.exists(path):
            diags.append(make(rule, fname, "committed file is missing"))
            continue
        with open(path) as f:
            on_disk = f.read()
        want = gen()
        if on_disk != want:
            # first diverging line makes the drift actionable
            got_lines = on_disk.splitlines()
            want_lines = want.splitlines()
            where = next((i for i, (a, b) in
                          enumerate(zip(got_lines, want_lines)) if a != b),
                         min(len(got_lines), len(want_lines)))
            diags.append(make(
                rule, f"{fname}:{where + 1}",
                "committed file differs from the generator output — "
                "regenerate via `python -m spark_rapids_tpu.lint "
                "--write-docs`"))


def regenerate_docs(repo_root: Optional[str] = None) -> List[str]:
    """Write SUPPORTED_OPS.md, CONFIGS.md and LOCKS.md from their
    generators; returns the files written (the CLI's --write-docs)."""
    from spark_rapids_tpu.conf import generate_docs
    from spark_rapids_tpu.overrides.docs import generate_supported_ops
    from spark_rapids_tpu.lockorder import generate_locks_md
    root = _repo_root(repo_root)
    written = []
    for fname, gen in (("SUPPORTED_OPS.md", generate_supported_ops),
                       ("CONFIGS.md", generate_docs),
                       ("LOCKS.md", generate_locks_md)):
        path = os.path.join(root, fname)
        with open(path, "w") as f:
            f.write(gen())
        written.append(path)
    return written


#: golden-corpus slice the metrics audit executes: one query per major
#: exec family (agg, join, sort+limit, window, exchange) — enough to
#: observe every hot exec class without running all 22
_METRICS_AUDIT_QUERIES = ("q1", "q3", "q5", "q6", "q7")


def audit_exec_metrics_tree(executable,
                            diags: List[Diagnostic],
                            context: str = "") -> None:
    """RA-ESSENTIAL-METRICS over ONE executed tree: every device exec
    (and the DeviceToHost root) that ran must carry the ESSENTIAL
    opTime/numOutputRows/numOutputBatches metrics. An exec whose
    metrics are entirely empty never ran (a lazily-pulled branch an
    early-terminating consumer abandoned) — skipped, EXCEPT the root,
    whose silence means the observation boundary was never installed."""
    from spark_rapids_tpu.execs.base import DeviceToHost, TpuExec
    from spark_rapids_tpu.lore import _iter_tree
    from spark_rapids_tpu.obs.metrics import ESSENTIAL_EXEC_METRICS

    root = executable
    for e in _iter_tree(executable):
        if not isinstance(e, (TpuExec, DeviceToHost)):
            continue
        name = type(e).__name__
        where = f"{context}{name}[loreId={getattr(e, '_lore_id', '?')}]"
        m = getattr(e, "metrics", None) or {}
        if not m:
            if e is root:
                diags.append(make(
                    "RA-ESSENTIAL-METRICS", where,
                    "root of an executed plan has NO metrics — the "
                    "observation boundary was never installed"))
            continue
        missing = [k for k in ESSENTIAL_EXEC_METRICS if k not in m]
        if missing:
            diags.append(make(
                "RA-ESSENTIAL-METRICS", where,
                f"executed exec is missing ESSENTIAL metric(s) "
                f"{', '.join(missing)}"))


def audit_exec_metrics(scale_factor: float = 0.005,
                       queries=_METRICS_AUDIT_QUERIES) -> List[Diagnostic]:
    """Execute a golden-corpus slice and assert every exec that ran
    emitted its ESSENTIAL metrics (the obs/spans.install_observation
    contract — an exec class overriding execute without riding the
    boundary shows up here, not as silently-missing tool data)."""
    from spark_rapids_tpu.lint.golden import _load_scale_test, golden_tables
    from spark_rapids_tpu.obs.spans import finalize_observation
    from spark_rapids_tpu.session import TpuSession

    st = _load_scale_test()
    tables = golden_tables(scale_factor)
    session = TpuSession()
    corpus = st.build_queries(session, tables)
    diags: List[Diagnostic] = []
    for name in queries:
        corpus[name]().collect_table()
        executable = session._last_executable
        finalize_observation(executable)
        audit_exec_metrics_tree(executable, diags, context=f"{name}:")
    return diags


#: declared keys consumed through a mechanism the text scan cannot see,
#: or seed-era reference-compat placeholders kept so carried-over
#: reference configs don't fail on unknown keys. Add "key: why"
#: entries, never bare keys — NEW keys must wire a reader.
_CONF_ORPHAN_ALLOWLIST: dict = {
    "spark.rapids.sql.reader.batchSizeRows":
        "seed placeholder: reference reader-batching knob; scans "
        "currently batch by bytes only",
    "spark.rapids.sql.hasNans":
        "seed placeholder: reference NaN-handling knob; device kernels "
        "handle NaN unconditionally",
    "spark.rapids.sql.castStringToTimestamp.enabled":
        "seed placeholder: reference cast gate; the cast is "
        "TypeSig-gated instead",
    "spark.rapids.sql.decimalType.enabled":
        "seed placeholder: reference decimal master switch; decimals "
        "gate per-op through TypeSig",
    "spark.rapids.sql.test.strictOracle":
        "seed placeholder: CPU-oracle strictness for a planned "
        "test-harness mode",
}


def _audit_conf_referenced(diags: List[Diagnostic], root: str) -> None:
    """RA-CONF-ORPHAN: every declared conf key must be CONSUMED by the
    engine or its harnesses — a key whose ConfEntry variable and key
    string both appear exactly once (their declaration) was added
    without wiring a reader, so setting it silently does nothing
    (the complement of RL-CONF-KEY, which catches references without a
    declaration). Kill switches are exempt: is_op_enabled reads them
    generically by name."""
    import re
    import sys

    from spark_rapids_tpu.conf import ConfEntry, registry

    sources = []
    pkg_dir = os.path.join(root, "spark_rapids_tpu")
    for dirpath, _dirs, names in os.walk(pkg_dir):
        for n in names:
            if n.endswith(".py"):
                sources.append(os.path.join(dirpath, n))
    for extra in ("bench.py", "scale_test.py"):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            sources.append(p)
    text = "\n".join(open(p, encoding="utf-8").read() for p in sources)

    #: key -> ConfEntry variable names bound in any engine module
    var_names: dict = {}
    for mod_name, mod in list(sys.modules.items()):
        if not mod_name.startswith("spark_rapids_tpu") or mod is None:
            continue
        for attr, val in list(vars(mod).items()):
            if isinstance(val, ConfEntry):
                var_names.setdefault(val.key, set()).add(attr)

    for key, entry in registry().items():
        parts = key.split(".")
        if (len(parts) == 5 and parts[:3] == ["spark", "rapids", "sql"]
                and parts[3] in ("exec", "expression")):
            continue  # kill switches: read generically by class name
        if key in _CONF_ORPHAN_ALLOWLIST:
            continue
        # boundary-aware: 'a.b' must not match inside 'a.b.c' — a key
        # that is a dotted prefix of another declared key would
        # otherwise count its sibling's declaration as a reference
        key_uses = len(re.findall(re.escape(key) + r"(?![.\w])", text))
        name_uses = sum(
            len(re.findall(rf"\b{re.escape(n)}\b", text))
            for n in var_names.get(key, ()))
        # one key-string occurrence (the declaration) + one occurrence
        # per variable binding (assignment/import) is declaration-only
        if key_uses <= 1 and name_uses <= len(var_names.get(key, ())):
            diags.append(make(
                "RA-CONF-ORPHAN", key,
                "conf key is declared but never read — wire a consumer "
                "or remove it (allowlist with a justification if it is "
                "consumed through a mechanism this scan cannot see)"))


def audit_registry(repo_root: Optional[str] = None) -> List[Diagnostic]:
    _import_full_package()
    diags: List[Diagnostic] = []
    _audit_unregistered(diags)
    _audit_param_arity(diags)
    _audit_kill_switches(diags)
    _audit_sql_exposure(diags)
    _audit_doc_drift(diags, _repo_root(repo_root))
    _audit_conf_referenced(diags, _repo_root(repo_root))
    return diags
