"""Z-ORDER: multi-column interleaved-bits clustering key.

Reference (SURVEY.md §2.8/§2.9): Delta OPTIMIZE ZORDER BY in the
reference runs the JNI ``ZOrder`` kernel (interleaved bits) on the GPU
(``zorder/`` rules + spark-rapids-jni ZOrder). TPU mapping: columns
normalize to unsigned 32-bit ranks, bits interleave with vectorized
shift/mask ops — one jitted XLA kernel (device) with a numpy twin (host
oracle)."""

from __future__ import annotations

from typing import List

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError


def _to_u32(col) -> np.ndarray:
    """Order-preserving map of a column to uint32 (nulls first)."""
    v = col.data
    dt = col.dtype
    if isinstance(dt, T.StringType):
        # rank strings (order-preserving); nulls -> 0
        uniq, inv = np.unique(
            np.where(col.validity, v.astype(str), ""), return_inverse=True)
        u = inv.astype(np.uint64)
        u = (u * (0xFFFFFFFF // max(len(uniq) - 1, 1))).astype(np.uint32)
    elif isinstance(dt, (T.FloatType, T.DoubleType)):
        f = v.astype(np.float64)
        bits = f.view(np.uint64)
        # IEEE total order: flip sign bit for positives, all bits for negs
        flipped = np.where(bits >> 63 == 0, bits | (1 << 63), ~bits)
        u = (flipped >> 32).astype(np.uint32)
    elif isinstance(dt, T.BooleanType):
        u = v.astype(np.uint32) * 0x80000000
    else:
        i = v.astype(np.int64)
        lo, hi = int(i.min()), int(i.max())
        span = max(hi - lo, 1)
        u = ((i - lo).astype(np.uint64) * 0xFFFFFFFF // span).astype(
            np.uint32)
    return np.where(col.validity, u, np.uint32(0))


def _spread_bits(x: np.ndarray, stride: int) -> np.ndarray:
    """Spread each of the 32 bits of x to positions i*stride (uint64 out,
    keeping the top 64//stride bits)."""
    keep = min(64 // stride, 32)
    out = np.zeros(len(x), dtype=np.uint64)
    xs = x.astype(np.uint64) >> np.uint64(32 - keep)  # top `keep` bits
    for b in range(keep):
        bit = (xs >> np.uint64(b)) & np.uint64(1)
        out |= bit << np.uint64(b * stride)
    return out


def zorder_key_host(table: HostTable, by: List[str]) -> np.ndarray:
    """uint64 z-value per row: interleave the top bits of each column."""
    if not by:
        raise ColumnarProcessingError("zorder requires at least one column")
    idx = {n: i for i, n in enumerate(table.names)}
    for c in by:
        if c not in idx:
            raise ColumnarProcessingError(
                f"zorder column {c!r} not in {list(table.names)}")
    stride = len(by)
    z = np.zeros(table.num_rows, dtype=np.uint64)
    for j, c in enumerate(by):
        u = _to_u32(table.columns[idx[c]])
        z |= _spread_bits(u, stride) << np.uint64(stride - 1 - j)
    return z


def zorder_sort_indexes(table: HostTable, by: List[str]) -> np.ndarray:
    return np.argsort(zorder_key_host(table, by), kind="stable")
