"""Multi-batch sort: spillable accumulation + device concat + sort
(reference analog: GpuSortExec out-of-core pending pool)."""

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col
from spark_rapids_tpu.plan.nodes import SortOrder

from tests.asserts import assert_tpu_and_cpu_are_equal
from tests.data_gen import DoubleGen, IntGen, StringGen, gen_table


@pytest.fixture(scope="module")
def stream_session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.batchSizeBytes": 1})


def _df(sess, gens, n=700, seed=17, num_batches=5):
    from spark_rapids_tpu.plan import from_host_table
    return from_host_table(gen_table(gens, n, seed), sess, num_batches)


GENS = {"i": IntGen(min_val=-100, max_val=100),
        "s": StringGen(cardinality=12), "d": DoubleGen()}


def test_streaming_sort_int(stream_session, cpu_session):
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).sort("i", "d"),
        stream_session, cpu_session, ignore_order=False)


def test_streaming_sort_string_desc_nulls(stream_session, cpu_session):
    """String keys need the union-dictionary remap across batches."""
    gens = {"s": StringGen(cardinality=9), "i": IntGen(null_prob=0.3)}
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, gens).sort(
            SortOrder(col("s"), ascending=False),
            SortOrder(col("i"), ascending=True, nulls_first=False)),
        stream_session, cpu_session, ignore_order=False)


def test_streaming_sort_with_injected_oom(cpu_session):
    from spark_rapids_tpu.session import TpuSession
    inj = TpuSession({"spark.rapids.sql.batchSizeBytes": 1,
                      "spark.rapids.sql.test.injectRetryOOM": "retry:2"})
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).sort("i"),
        inj, cpu_session, ignore_order=False)


def test_streaming_sort_then_limit_releases_batches(stream_session):
    """A downstream limit abandons the stream; no spill registrations may
    leak (ADVICE r1: coalesce/pending spillables on abandonment)."""
    from spark_rapids_tpu.runtime.spill import BufferCatalog
    catalog = BufferCatalog.get()
    before = len(catalog._entries) if hasattr(catalog, "_entries") else None
    out = _df(stream_session, GENS).sort("i").limit(3).collect_table()
    assert out.num_rows == 3
    if before is not None:
        assert len(catalog._entries) <= before


def test_streaming_sort_after_streaming_agg(stream_session, cpu_session):
    """Pipeline: streaming aggregate feeding a sort."""
    assert_tpu_and_cpu_are_equal(
        lambda s: _df(s, GENS).group_by("s").agg(
            F.count().alias("c"), F.sum(col("i")).alias("si")).sort("s"),
        stream_session, cpu_session, ignore_order=False)
