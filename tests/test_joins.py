"""Device join tests vs the CPU oracle (reference: integration_tests
join_test.py matrix — SURVEY.md §4)."""

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.ops.expr import col, lit
from tests.asserts import assert_runs_on_tpu, assert_tpu_and_cpu_are_equal
from tests.data_gen import (
    BooleanGen,
    DateGen,
    DoubleGen,
    IntGen,
    LongGen,
    StringGen,
    gen_table,
)

ALL_JOIN_TYPES = ["inner", "left", "right", "full", "leftsemi", "leftanti"]


def _join_inputs(key_gen, n_left=300, n_right=200, seed=11):
    left = gen_table({"k": key_gen, "lv": LongGen()}, n_left, seed=seed)
    right = gen_table({"k": key_gen, "rv": LongGen()}, n_right, seed=seed + 1)
    return left, right


def _build_join(left, right, how, on="k"):
    def build(s):
        ldf = s.create_dataframe(left)
        rdf = s.create_dataframe(right)
        return ldf.join(rdf, on=on, how=how)
    return build


@pytest.mark.parametrize("how", ALL_JOIN_TYPES)
@pytest.mark.parametrize("keygen", [
    IntGen(min_val=0, max_val=50),          # many matches
    LongGen(),                               # mostly no matches
    StringGen(cardinality=30),
    DateGen(),
    BooleanGen(),
], ids=["int_dense", "long_sparse", "string", "date", "bool"])
def test_join_types_and_keys(session, cpu_session, how, keygen):
    left, right = _join_inputs(keygen)
    assert_tpu_and_cpu_are_equal(_build_join(left, right, how),
                                 session, cpu_session)


@pytest.mark.parametrize("how", ["inner", "left", "full"])
def test_join_multi_key(session, cpu_session, how):
    left = gen_table({"a": IntGen(min_val=0, max_val=10),
                      "b": StringGen(cardinality=5), "lv": LongGen()}, 250, seed=3)
    right = gen_table({"a": IntGen(min_val=0, max_val=10),
                       "b": StringGen(cardinality=5), "rv": DoubleGen()}, 150, seed=4)
    assert_tpu_and_cpu_are_equal(
        _build_join(left, right, how, on=["a", "b"]), session, cpu_session,
        approximate_float=True)


def test_join_runs_on_tpu(session):
    left, right = _join_inputs(IntGen(min_val=0, max_val=20))
    assert_runs_on_tpu(_build_join(left, right, "inner"), session)


def test_join_nan_keys_match(session, cpu_session):
    """Spark join keys: NaN == NaN, -0.0 == 0.0."""
    left = HostTable.from_pydict(
        {"k": [float("nan"), 0.0, 1.5, None], "lv": [1, 2, 3, 4]},
        dtypes={"k": T.DOUBLE})
    right = HostTable.from_pydict(
        {"k": [float("nan"), -0.0, 2.5, None], "rv": [10, 20, 30, 40]},
        dtypes={"k": T.DOUBLE})
    assert_tpu_and_cpu_are_equal(_build_join(left, right, "inner"),
                                 session, cpu_session)
    assert_tpu_and_cpu_are_equal(_build_join(left, right, "full"),
                                 session, cpu_session)


def test_join_null_keys_never_match(session, cpu_session):
    left = HostTable.from_pydict({"k": [1, None, 3], "lv": [1, 2, 3]})
    right = HostTable.from_pydict({"k": [None, 1, 3], "rv": [10, 20, 30]})
    for how in ALL_JOIN_TYPES:
        assert_tpu_and_cpu_are_equal(_build_join(left, right, how),
                                     session, cpu_session)


def test_join_type_promotion(session, cpu_session):
    """INT keys join LONG keys through an implicit cast."""
    left = HostTable.from_pydict({"k": [1, 2, 3], "lv": [1, 2, 3]},
                                 dtypes={"k": T.INT, "lv": T.LONG})
    right = HostTable.from_pydict({"k": [2, 3, 4], "rv": [20, 30, 40]},
                                  dtypes={"k": T.LONG, "rv": T.LONG})

    def build(s):
        ldf = s.create_dataframe(left)
        rdf = s.create_dataframe(right)
        from spark_rapids_tpu.plan import nodes as P
        return ldf._wrap(P.Join(ldf.plan, rdf.plan, "inner",
                                [col("k")], [col("k")]))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_cross_join(session, cpu_session):
    left = HostTable.from_pydict({"a": [1, 2, 3]})
    right = HostTable.from_pydict({"b": ["x", "y"]})
    assert_tpu_and_cpu_are_equal(
        lambda s: s.create_dataframe(left).join(s.create_dataframe(right)),
        session, cpu_session)


def test_inner_join_with_condition(session, cpu_session):
    left, right = _join_inputs(IntGen(min_val=0, max_val=10))

    def build(s):
        from spark_rapids_tpu.plan import nodes as P
        ldf = s.create_dataframe(left)
        rdf = s.create_dataframe(right)
        cond = col("lv") < col("rv")
        return ldf._wrap(P.Join(ldf.plan, rdf.plan, "inner",
                                [col("k")], [col("k")], cond))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_outer_join_with_condition_falls_back(session, cpu_session):
    left, right = _join_inputs(IntGen(min_val=0, max_val=10), 50, 50)

    def build(s):
        from spark_rapids_tpu.plan import nodes as P
        ldf = s.create_dataframe(left)
        rdf = s.create_dataframe(right)
        cond = col("lv") < col("rv")
        return ldf._wrap(P.Join(ldf.plan, rdf.plan, "left",
                                [col("k")], [col("k")], cond))

    from spark_rapids_tpu.overrides import wrap_plan
    meta = wrap_plan(build(session).plan, session.conf)
    assert not meta.can_run_on_tpu
    assert any("non-equi condition" in r for r in meta.reasons)
    # correctness still holds through the CPU fallback
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_join_empty_sides(session, cpu_session):
    empty = HostTable.from_pydict({"k": [], "lv": []},
                                  dtypes={"k": T.INT, "lv": T.LONG})
    data = HostTable.from_pydict({"k": [1, 2], "rv": [10, 20]},
                                 dtypes={"k": T.INT, "rv": T.LONG})
    for how in ALL_JOIN_TYPES:
        assert_tpu_and_cpu_are_equal(_build_join(empty, data, how),
                                     session, cpu_session)
        assert_tpu_and_cpu_are_equal(_build_join(data, empty, how),
                                     session, cpu_session)


def test_join_then_aggregate(session, cpu_session):
    """Joins compose with downstream device aggregation."""
    from spark_rapids_tpu import functions as F
    left, right = _join_inputs(IntGen(min_val=0, max_val=5, null_prob=0.0))

    def build(s):
        j = _build_join(left, right, "inner")(s)
        return j.group_by("k").agg(F.count("rv").alias("c"),
                                   F.sum("lv").alias("sl"))
    assert_tpu_and_cpu_are_equal(build, session, cpu_session)


def test_join_duplicate_build_keys(session, cpu_session):
    """Multiple matches per probe row expand correctly."""
    left = HostTable.from_pydict({"k": [1, 1, 2], "lv": [1, 2, 3]})
    right = HostTable.from_pydict({"k": [1, 1, 1, 2, 2], "rv": [1, 2, 3, 4, 5]})
    for how in ["inner", "left", "full"]:
        assert_tpu_and_cpu_are_equal(_build_join(left, right, how),
                                     session, cpu_session)
