"""JSON expressions.

Reference (SURVEY.md §2.3/§2.9): ``GpuGetJsonObject.scala`` backed by the
JNI ``JSONUtils`` kernel and ``GpuJsonToStructs``; the reference treats
get_json_object as first-class (it has a dedicated native parser).

TPU mapping: JSON documents are string columns = dictionary-encoded on
device, so extraction runs ONCE per DISTINCT document on the host
(stdlib json) and the device gathers results by code — the
dictionary-transform pattern every string function here uses. Spark
semantics: '$'-rooted paths with .field / ['field'] / [index] / [*]
steps; strings return unquoted, other scalars their JSON literal,
objects/arrays compact JSON, anything unresolvable -> null."""

from __future__ import annotations

import json
import re
from typing import List, Optional, Union

from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.expr import Expression, Literal
from spark_rapids_tpu.ops.strings import DictStringToString

_STEP_RE = re.compile(
    r"\.(?P<field>[A-Za-z_][A-Za-z0-9_]*)"
    r"|\[\s*'(?P<qfield>[^']*)'\s*\]"
    r"|\[\s*\"(?P<dqfield>[^\"]*)\"\s*\]"
    r"|\[\s*(?P<index>\d+)\s*\]"
    r"|\[\s*(?P<star>\*)\s*\]")


def parse_json_path(path: str) -> Optional[List[Union[str, int]]]:
    """'$.a[0].b' -> ['a', 0, 'b']; '*' marks a wildcard step; None for
    malformed paths (Spark: whole expression yields null)."""
    if not path or path[0] != "$":
        return None
    steps: List[Union[str, int]] = []
    pos = 1
    while pos < len(path):
        m = _STEP_RE.match(path, pos)
        if m is None:
            return None
        if m.group("field") is not None:
            steps.append(m.group("field"))
        elif m.group("qfield") is not None:
            steps.append(m.group("qfield"))
        elif m.group("dqfield") is not None:
            steps.append(m.group("dqfield"))
        elif m.group("index") is not None:
            steps.append(int(m.group("index")))
        else:
            steps.append("*")
        pos = m.end()
    return steps


def _walk(value, steps: List[Union[str, int]], depth: int = 0):
    """Returns (matched, result) where wildcard steps collect lists."""
    if depth == len(steps):
        return True, value
    step = steps[depth]
    if step == "*":
        if not isinstance(value, list):
            return False, None
        out = []
        for item in value:
            ok, r = _walk(item, steps, depth + 1)
            if ok:
                out.append(r)
        if not out:
            return False, None
        return True, out if len(out) > 1 else out[0]
    if isinstance(step, int):
        if isinstance(value, list) and 0 <= step < len(value):
            return _walk(value[step], steps, depth + 1)
        return False, None
    if isinstance(value, dict) and step in value:
        return _walk(value[step], steps, depth + 1)
    return False, None


def extract_json(doc: str, steps: List[Union[str, int]]) -> Optional[str]:
    try:
        value = json.loads(doc)
    except (ValueError, TypeError):
        return None
    ok, r = _walk(value, steps)
    if not ok or r is None:
        return None
    if isinstance(r, str):
        return r  # strings unquote (Spark)
    if isinstance(r, bool):
        return "true" if r else "false"
    if isinstance(r, (int, float)):
        return json.dumps(r)
    return json.dumps(r, separators=(",", ":"))


class GetJsonObject(DictStringToString):
    """get_json_object(json, path) — path must be a literal (the
    reference requires a foldable path too)."""

    def __init__(self, child: Expression, path: Expression):
        self.children = (child, path)
        self._steps = None
        if isinstance(path, Literal) and path.value is not None:
            self._steps = parse_json_path(str(path.value))

    def with_children(self, children):
        return GetJsonObject(children[0], children[1])

    def key(self):
        p = self.children[1]
        pv = str(p.value) if isinstance(p, Literal) else None
        return ("get_json_object", pv, self.children[0].key())

    @property
    def device_supported(self):
        return isinstance(self.children[1], Literal)

    def transform(self, s: str) -> Optional[str]:
        if self._steps is None:
            return None  # malformed literal path -> null per row (Spark)
        return extract_json(s, self._steps)

    def eval_cpu(self, table):
        if isinstance(self.children[1], Literal):
            return super().eval_cpu(table)
        # non-literal path: the CPU fallback evaluates it PER ROW
        import numpy as np
        from spark_rapids_tpu.columnar import HostColumn
        doc = self.children[0].eval_cpu(table)
        pth = self.children[1].eval_cpu(table)
        n = len(doc)
        out = np.empty(n, dtype=object)
        validity = (doc.validity & pth.validity).copy()
        for i in range(n):
            r = None
            if validity[i]:
                steps = parse_json_path(str(pth.data[i]))
                if steps is not None:
                    r = extract_json(doc.data[i], steps)
            out[i] = r
            validity[i] = r is not None
        return HostColumn(T.STRING, out, validity)


def json_tuple(json_expr, *fields):
    """json_tuple(col, 'f1', 'f2', ...) expands to one top-level field
    extraction per name (Spark plans JsonTuple via Generate; the
    extraction semantics are the GetJsonObject fast path c0..cN)."""
    from spark_rapids_tpu.ops.expr import col as _col, lit as _lit
    e = _col(json_expr) if isinstance(json_expr, str) else json_expr
    out = []
    for i, f in enumerate(fields):
        if not isinstance(f, str):
            raise ColumnarProcessingError("json_tuple fields must be "
                                          "string literals")
        out.append(GetJsonObject(e, _lit(f"$.{f}")).alias(f"c{i}"))
    return out
