"""Profiler + crash-handler tests (reference: profiler.scala,
GpuCoreDumpHandler.scala, DumpUtils.scala, RangeConfMatcher — SURVEY §5)."""

import json
import os

import numpy as np
import pytest

from spark_rapids_tpu.runtime.profiler import TpuProfiler, parse_ranges


def test_parse_ranges():
    assert parse_ranges("1-3,8") == {1, 2, 3, 8}
    assert parse_ranges("") is None
    assert parse_ranges("5") == {5}
    assert parse_ranges(" 0-1 , 4 ") == {0, 1, 4}


def test_profiler_query_ranges(tmp_path):
    from spark_rapids_tpu.conf import RapidsConf
    conf = RapidsConf({
        "spark.rapids.profile.enabled": "true",
        "spark.rapids.profile.pathPrefix": str(tmp_path),
        "spark.rapids.profile.queryRanges": "1"})
    p = TpuProfiler(conf)
    assert not p.should_profile(0)
    assert p.should_profile(1)
    assert not p.should_profile(2)


def test_profiler_collects_trace(tmp_path):
    """An enabled profiler writes an Xprof trace dir for the profiled
    query (CPU-mesh jax works with the profiler too)."""
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({
        "spark.rapids.profile.enabled": "true",
        "spark.rapids.profile.pathPrefix": str(tmp_path),
        "spark.rapids.profile.queryRanges": "0"})
    df = s.create_dataframe({"x": np.arange(100, dtype=np.int64)})
    assert df.select("x").count() == 100
    qdir = tmp_path / "query_0"
    assert qdir.is_dir()
    # jax writes plugins/profile/<ts>/ under the trace dir
    found = list(qdir.rglob("*.xplane.pb")) + list(qdir.rglob("*.json.gz")) \
        + list(qdir.rglob("*.trace*"))
    assert s.profiler.sessions_written == 1
    assert found, f"no trace artifacts under {qdir}"


def test_fatal_classification():
    from spark_rapids_tpu.runtime.crash_handler import is_fatal_device_error

    class XlaRuntimeError(Exception):
        pass

    assert is_fatal_device_error(XlaRuntimeError("INTERNAL: device halted"))
    assert not is_fatal_device_error(
        XlaRuntimeError("RESOURCE_EXHAUSTED: Out of memory"))
    assert not is_fatal_device_error(ValueError("INTERNAL"))


def test_crash_report_written(tmp_path):
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.runtime.crash_handler import write_crash_report
    conf = RapidsConf({"spark.rapids.memory.crashDump.dir": str(tmp_path)})
    try:
        raise RuntimeError("XlaRuntimeError: INTERNAL: boom")
    except RuntimeError as e:
        path = write_crash_report(e, conf, plan_description="* Scan")
    assert path and os.path.exists(path)
    report = json.load(open(path))
    assert "boom" in report["exception"]
    assert report["plan"] == "* Scan"
    assert "thread_dump" in report
    assert "buffer_catalog" in report


def test_dump_table(tmp_path):
    import pyarrow.parquet as pq
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.runtime.crash_handler import dump_table
    t = HostTable.from_pydict({"x": np.arange(10, dtype=np.int64)})
    p = dump_table(t, str(tmp_path / "d.parquet"))
    assert pq.read_table(p).num_rows == 10
