"""Shim provider for jax >= 0.6: the canonical (base) surface IS this
version family's surface — top-level ``jax.shard_map``, ``jax.tree.*``,
``jax.make_mesh`` all exist."""

from __future__ import annotations

from spark_rapids_tpu.shims.base import BaseShim


class JaxCurrentShim(BaseShim):
    MIN_VERSION = (0, 6, 0)
    MAX_VERSION = (2, 0, 0)
