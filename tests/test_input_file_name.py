"""input_file_name() / input_file_block_start / length (reference:
InputFileBlockRule.scala + GpuInputFileName family). The engine rewrites
the plan so the scan attaches per-row provenance columns; these tests pin
selection, grouping, filtering, partitioned scans, reader modes, no-info
fallback above joins, and the hidden-column leak guard."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.ops.expr import col, lit


@pytest.fixture
def three_files(tmp_path):
    for i in range(3):
        pq.write_table(
            pa.table({"a": pa.array([i * 10 + 1, i * 10 + 2],
                                    type=pa.int64())}),
            str(tmp_path / f"f{i}.parquet"))
    return str(tmp_path / "*.parquet")


def test_select_name_start_length(session, cpu_session, three_files):
    def q(s):
        return sorted(s.read_parquet(three_files).select(
            col("a"), F.input_file_name().alias("f"),
            F.input_file_block_start().alias("st"),
            F.input_file_block_length().alias("ln")).collect())
    a, b = q(session), q(cpu_session)
    assert a == b
    assert len({r[1] for r in a}) == 3
    assert all(r[1].endswith(".parquet") for r in a)
    assert all(r[2] == 0 and r[3] > 0 for r in a)


def test_group_by_file(session, three_files):
    g = sorted(session.read_parquet(three_files).group_by(
        F.input_file_name().alias("f")).agg(F.count().alias("c")).collect())
    assert len(g) == 3 and all(r[1] == 2 for r in g)


def test_filter_hides_provenance_columns(session, cpu_session, three_files):
    def q(s):
        return sorted(s.read_parquet(three_files).filter(
            F.like(F.input_file_name(), "%f1%")).collect())
    a, b = q(session), q(cpu_session)
    assert a == b == [(11,), (12,)]


def test_no_info_above_join(session):
    df1 = session.create_dataframe({"k": np.array([1, 2], dtype=np.int64)})
    df2 = session.create_dataframe({"k": np.array([1, 2], dtype=np.int64)})
    r = df1.join(df2, on=["k"]).select(
        F.input_file_name().alias("f"),
        F.input_file_block_start().alias("st")).collect()
    assert all(x == ("", -1) for x in r)


def test_partitioned_scan_keeps_partition_and_provenance(session, tmp_path):
    for p in (0, 1):
        d = tmp_path / f"p={p}"
        d.mkdir()
        pq.write_table(pa.table({"a": pa.array([p, p + 10],
                                               type=pa.int64())}),
                       str(d / "x.parquet"))
    got = sorted(session.read_parquet(str(tmp_path / "*" / "*.parquet"))
                 .select(col("a"), col("p"),
                         F.input_file_name().alias("f")).collect())
    assert len(got) == 4
    assert all(f"p={r[1]}" in r[2] for r in got)


def test_reader_modes_agree(session, three_files):
    want = None
    for mode in ("PERFILE", "MULTITHREADED", "COALESCING"):
        got = sorted(session.read_parquet(
            three_files, reader_type=mode).select(
            col("a"), F.input_file_name().alias("f")).collect())
        if want is None:
            want = got
        else:
            assert got == want, mode


def test_rewrite_is_idempotent(session, three_files):
    df = session.read_parquet(three_files).select(
        F.input_file_name().alias("f"))
    a = sorted(df.collect())
    b = sorted(df.collect())  # second execute re-runs the rewrite
    assert a == b and len(a) == 6


def test_shared_scan_node_not_polluted(session, three_files):
    """Code-review r5: the rewrite is copy-on-write — a base DataFrame
    sharing the scan node with an input_file query must not grow hidden
    columns in its own results."""
    base = session.read_parquet(three_files)
    with_file = base.select(F.input_file_name().alias("f"))
    assert len(with_file.collect()) == 6
    # the sibling query sees the ORIGINAL scan schema
    plain = sorted(base.collect())
    assert all(len(r) == 1 for r in plain), plain[:2]
    from spark_rapids_tpu.io.common import FileScanNode

    def find_scan(n):
        if isinstance(n, FileScanNode):
            return n
        for c in getattr(n, "children", ()):
            got = find_scan(c)
            if got is not None:
                return got
        return None
    assert find_scan(base.plan).provide_file_info is False


def test_two_intermediate_projects(session, three_files):
    """Code-review r5: passthrough columns thread BOTTOM-UP through
    multiple stacked projects."""
    got = sorted(session.read_parquet(three_files)
                 .select(col("a"))
                 .select(col("a"))
                 .select(col("a"), F.input_file_name().alias("f"))
                 .collect())
    assert len(got) == 6 and len({r[1] for r in got}) == 3


def test_join_above_input_file_filter(session, three_files):
    """Code-review r5: a filter on input_file_name feeding a join must
    not shift the join's right-side ordinals (the hidden columns are
    dropped before the join sees them)."""
    left = session.read_parquet(three_files).filter(
        F.like(F.input_file_name(), "%f1%")).with_column("k", col("a"))
    right = session.create_dataframe(
        {"k": np.array([11, 12], dtype=np.int64),
         "w": np.array([100, 200], dtype=np.int64)})
    got = sorted(left.join(right, on=["k"], how="inner")
                 .select(col("k"), col("w")).collect())
    assert got == [(11, 100), (12, 200)]


def test_sort_by_input_file_name(session, three_files):
    """Code-review r5: input_file_* as a SORT key is substituted (it
    lives in Sort.orders, not an expr list)."""
    got = [r[0] for r in session.read_parquet(three_files)
           .sort(F.input_file_name(), ascending=False).collect()]
    # descending by file path: f2 rows first, then f1, then f0
    assert got[:2] == [21, 22] and got[-2:] == [1, 2]
