"""Mesh runtime: the engine's device mesh as a FIRST-CLASS runtime object.

The paper's target is a v5e-256 pod; the dryrun harness
(``__graft_entry__.dryrun_multichip``) already models the hierarchical
``(dcn, ici)`` mesh shape but the engine itself ran every query on one
chip.  This module promotes the mesh to conf-driven engine state, owned
by :class:`~spark_rapids_tpu.runtime.device_manager.TpuDeviceManager`:

* ``spark.rapids.mesh.enabled`` turns mesh-native execution on;
* ``spark.rapids.mesh.shape`` declares the topology — ``""`` (all local
  devices on one flat axis), ``"8"`` (explicit 1-D size) or ``"2x4"``
  (hierarchical: ``dcn`` x ``ici``, the multi-host slice layout — heavy
  all-to-alls ride the fast inner axis, only merged partials cross dcn);
* ``spark.rapids.mesh.axis`` names the flat row axis (default ``data``).

Reconfiguration bumps a **generation** counter: the executable cache
folds it into its coherency token, so a converted tree checked out
before a mesh change can neither serve nor re-park after it, and the
plan fingerprint folds the mesh **identity token** so cached plans never
cross mesh configs.

Host-transfer discipline (the PERF.md cost model: every h2d upload
mid-pipeline is a ~0.15-3.3s stall on the tunneled TPU): shards land
per-device with ``jax.device_put`` once at the scan, stay device-resident
between exchanges, and the only sanctioned device->host materialization
point in mesh code is :func:`mesh_gather` (the exchange's live-count
fetch routes through it) — enforced statically by the RL-MESH-HOST
lint rule.

The mesh, like the device topology it models, is PROCESS state (one
MeshRuntime, owned by TpuDeviceManager — the same contract as HEALTH
and the circuit breaker). Concurrent sessions whose confs disagree on
the mesh reconfigure it per query: results stay bit-identical either
way (the re-land boundaries guarantee layout independence), but each
effective change bumps the generation — alternating mesh/non-mesh
sessions therefore thrash the executable cache by design (cached
trees never cross mesh configs). Tenants of one QueryService share
one session/conf and never hit this.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Optional, Tuple

import numpy as np

from spark_rapids_tpu.conf import RapidsConf, bool_conf, int_conf, str_conf
from spark_rapids_tpu.obs.metrics import metric_scope, register_metric
from spark_rapids_tpu.lockorder import ordered_lock

MESH_ENABLED = bool_conf(
    "spark.rapids.mesh.enabled", False,
    "Mesh-native distributed execution: partitioned scans land their "
    "shards directly per-device over the conf-declared device mesh "
    "(spark.rapids.mesh.shape), tables carry a NamedSharding row "
    "descriptor through the plan, and every supported shuffle exchange "
    "lowers to the ICI all-to-all collective (host-file shuffle stays "
    "the fallback, with the demotion reason surfaced in explain()). "
    "Mesh identity folds into the plan fingerprint and the executable "
    "cache's generation, so cached plans never cross mesh configs.",
    commonly_used=True)

MESH_SHAPE = str_conf(
    "spark.rapids.mesh.shape", "",
    "Device-mesh topology for mesh-native execution: '' uses every "
    "local device on one flat axis, 'N' is an explicit 1-D size, and "
    "'DxI' builds the hierarchical (dcn, ici) mesh the multichip "
    "dryrun models — all-to-all shuffles ride the fast inner ici axis. "
    "The device count must not exceed the backend's local device count.")

MESH_AXIS = str_conf(
    "spark.rapids.mesh.axis", "data",
    "Name of the flat row axis of a 1-D mesh (hierarchical 'DxI' "
    "shapes always use ('dcn', 'ici')). Row-sharded tables carry a "
    "PartitionSpec over this axis.")

MESH_MAX_SHARD_RETRIES = int_conf(
    "spark.rapids.mesh.maxShardRetries", 2,
    "Local re-gathers a mesh gather boundary may pay before failing "
    "typed: when the row-count+checksum validation at a MeshReland "
    "(or the ICI exchange's verified live-count fetch) trips, the "
    "boundary re-lands from the still-intact sharded source up to "
    "this many times (shardRetries counter) and then raises "
    "MeshGatherError — which the query-replay machinery re-lands "
    "from the scan cache rather than surfacing wrong results.")

MESH_DEGRADE_MAX_SHRINKS = int_conf(
    "spark.rapids.mesh.degrade.maxShrinks", 2,
    "Mesh reconfigurations onto surviving devices the degradation "
    "ladder (runtime/health.py) may perform after repeated PARTIAL "
    "device losses (one mesh device dead, backend otherwise alive) "
    "before escalating to a full backend reinitialization and, "
    "ultimately, the CPU-only latch. Each shrink excludes the "
    "suspect device, bumps the mesh generation (fencing every "
    "cached tree/dictionary) and is surfaced in QueryService."
    "health(), explain() and the event log.")

MESH_GATHER_VERIFY = bool_conf(
    "spark.rapids.mesh.gather.verify", True,
    "Row-count + checksum validation at mesh gather boundaries (the "
    "TPAK-v2 frame-CRC pattern applied to the MeshReland device-to-"
    "device gather and the ICI exchange's live-count fetch): a "
    "corrupted shard raises a retryable error and re-lands from the "
    "intact sharded source instead of producing silently wrong "
    "results. Costs two tiny digest kernels plus one small host "
    "fetch per physical re-land; disable only for benchmarking.")

# -- the `mesh` metric scope -------------------------------------------------

register_metric("shardsDispatched", "count", "ESSENTIAL",
                "table shards landed per-device by mesh-native scans "
                "(one per device per sharded upload)")
register_metric("iciExchanges", "count", "ESSENTIAL",
                "shuffle exchanges lowered to the ICI all-to-all "
                "collective instead of the host-file shuffle")
register_metric("iciBytes", "bytes", "ESSENTIAL",
                "payload bytes moved through ICI all-to-all collectives "
                "(column data + validity, the exchanged row shards)")
register_metric("meshGatherRows", "count", "MODERATE",
                "elements materialized to host through the sanctioned "
                "mesh_gather point (per-partition live counts of each "
                "ICI exchange — the one host sync a collective pays)")
register_metric("hostShuffleFallbacks", "count", "ESSENTIAL",
                "shuffle exchanges that requested the mesh/ICI path but "
                "demoted to the host-file shuffle (reason surfaced in "
                "explain() and the exchange's describe())")
register_metric("meshHostUploads", "count", "MODERATE",
                "host->device transfers performed inside mesh exchange "
                "dispatch — 0 on a warm mesh query (shards device-"
                "resident, dictionary bytes interned)")
register_metric("meshRelandRows", "count", "MODERATE",
                "row slots re-landed from the sharded layout into the "
                "single-device layout at wide-kernel boundaries "
                "(execs/mesh.py — device-to-device, never host)")
register_metric("meshDictInterns", "count", "MODERATE",
                "string-dictionary byte matrices replicated across the "
                "mesh and interned by dictionary identity (repeated "
                "exchanges over one dictionary pay replication once)")
register_metric("shardRetries", "count", "ESSENTIAL",
                "local re-gathers paid at mesh gather boundaries after "
                "a failed row-count/checksum validation (bounded by "
                "spark.rapids.mesh.maxShardRetries)")
register_metric("gatherChecksFailed", "count", "ESSENTIAL",
                "row-count/checksum validations that tripped at a mesh "
                "gather boundary (MeshReland or the ICI live-count "
                "fetch) — each one is a corrupted shard CAUGHT instead "
                "of served")

MESH_SCOPE = metric_scope("mesh")

#: runtime tunables pushed by PlacementLayer.apply_tuning_confs (execs
#: and the exchange hold no conf handle — the SS.BLOCK pattern)
MAX_SHARD_RETRIES = 2
GATHER_VERIFY = True


def _parse_shape(shape: str, avail: int) -> Tuple[int, ...]:
    """'', 'N' or 'DxI' -> dims tuple. Raises on malformed shapes or
    shapes wider than the available device count."""
    from spark_rapids_tpu.errors import ColumnarProcessingError
    s = shape.strip().lower()
    if not s:
        return (avail,)
    parts = s.replace("*", "x").split("x")
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise ColumnarProcessingError(
            f"spark.rapids.mesh.shape must be '', 'N' or 'DxI', got "
            f"{shape!r}")
    if len(dims) > 2 or any(d < 1 for d in dims):
        raise ColumnarProcessingError(
            f"spark.rapids.mesh.shape supports 1-D 'N' or 2-D 'DxI' "
            f"positive dims, got {shape!r}")
    total = 1
    for d in dims:
        total *= d
    if total > avail:
        raise ColumnarProcessingError(
            f"spark.rapids.mesh.shape={shape!r} needs {total} devices "
            f"but only {avail} are available")
    return dims


#: per-ATTEMPT mesh suppression (the "re-land single-device" rung of
#: the degradation ladder): a session replaying a query after repeated
#: mesh device losses sets this around the attempt, and every
#: placement-relevant reader below (enabled / scan_placement /
#: effective_ndev / identity_token / shape_str) reports the mesh OFF
#: for THIS THREAD only — the process mesh, and concurrent workers'
#: queries, are untouched. The demotion reason surfaces through the
#: existing hostShuffleFallbacks / explain() machinery
#: (execs/exchange.ici_demotion_reason reads it).
_SUPPRESS: "ContextVar[Optional[str]]" = ContextVar(
    "mesh_suppress", default=None)


def suppression_reason() -> Optional[str]:
    """Why THIS thread's in-flight attempt must land single-device
    (None when mesh execution is not suppressed)."""
    return _SUPPRESS.get()


@contextmanager
def suppressed_mesh(reason: str):
    """Scope one execution attempt's single-device demotion (the
    degradation ladder's middle rung)."""
    tok = _SUPPRESS.set(reason)
    try:
        yield
    finally:
        _SUPPRESS.reset(tok)


class MeshRuntime:
    """Process-wide mesh state (owned by TpuDeviceManager, configured
    per query by the placement layer). Reconfiguration is coherency-
    relevant: the generation bumps whenever the effective (enabled,
    dims, axis, devices) tuple changes, and both caches consult it.

    The FAULT-DOMAIN half (this PR): ``_excluded_ids`` holds devices
    the degradation ladder evicted after partial losses — configure()
    builds the mesh from the survivors (collapsing to a flat 1-D mesh
    when the declared shape no longer fits), ``shrink_excluding``/
    ``restore`` walk the set, and the exclusion folds into the config
    key so every shrink/restore rebuilds and bumps the generation
    (fencing stale cached trees and dictionaries exactly like a conf
    reconfiguration)."""

    def __init__(self):
        self._lock = ordered_lock("mesh.runtime")
        self._mesh = None
        self._dims: Tuple[int, ...] = ()
        self._axes: Tuple[str, ...] = ()
        self._enabled = False
        self._config_key = None
        self._generation = 0
        #: devices evicted by the degradation ladder (persist across
        #: queries until restore(); folded into the config key)
        self._excluded_ids: frozenset = frozenset()
        #: why the mesh is running below declared strength (None at
        #: full strength) — surfaced in health()/explain()/event log
        self._degraded_reason: Optional[str] = None
        #: the declared shape the degraded mesh fell back from
        self._declared_shape: Optional[str] = None

    # -- configuration -------------------------------------------------------
    def configure(self, conf: RapidsConf) -> None:
        """Apply the session's mesh conf. Cheap when unchanged; a real
        change rebuilds the mesh and bumps the generation. The config
        key folds HEALTH's backend generation: a device-loss reinit
        (runtime/health.py) replaces every jax Device object, and a
        mesh built from the dead backend must be rebuilt on the next
        prepare even though the conf tuple — and the surviving device
        IDS the identity token hashes — are unchanged."""
        from spark_rapids_tpu.errors import ColumnarProcessingError
        from spark_rapids_tpu.runtime.health import HEALTH
        enabled = bool(conf.get_entry(MESH_ENABLED))
        shape = str(conf.get_entry(MESH_SHAPE))
        axis = str(conf.get_entry(MESH_AXIS)).strip() or "data"
        with self._lock:
            excluded = self._excluded_ids
        key = (enabled, shape.strip().lower(), axis, HEALTH.generation(),
               excluded)
        with self._lock:
            if key == self._config_key:
                return
        # build OUTSIDE the lock (jax device discovery can be slow); the
        # publish below re-checks the key so racing configurers converge
        mesh = None
        dims: Tuple[int, ...] = ()
        axes: Tuple[str, ...] = ()
        if enabled:
            import jax
            from jax.sharding import Mesh
            devices = [d for d in jax.devices()
                       if d.id not in excluded]
            try:
                dims = _parse_shape(shape, len(devices))
            except ColumnarProcessingError:
                if not (excluded and devices):
                    raise
                # the declared shape no longer fits the SURVIVORS: the
                # degraded mesh collapses to one flat axis over every
                # remaining device (hierarchical shapes included — a
                # partial pod cannot honor the declared (dcn, ici)
                # factorization, and correctness never depended on it:
                # wide kernels re-land regardless of mesh width)
                dims = (len(devices),)
            axes = ("dcn", "ici") if len(dims) == 2 else (axis,)
            total = 1
            for d in dims:
                total *= d
            mesh = Mesh(np.array(devices[:total]).reshape(dims), axes)
        with self._lock:
            if key == self._config_key:
                return
            self._mesh = mesh
            self._dims = dims
            self._axes = axes
            self._enabled = enabled
            self._config_key = key
            self._declared_shape = shape.strip() or None
            self._generation += 1

    # -- the degradation ladder's mesh half ----------------------------------
    def shrink_excluding(self, device_id: Optional[int],
                         reason: str) -> bool:
        """Evict one device from the mesh fault domain: ``device_id``
        when the failure named it, else the mesh's LAST device (the
        deterministic choice for injected losses). The exclusion folds
        into the config key, so the next configure() rebuilds the mesh
        from the survivors and bumps the generation — every cached
        tree, scan image and replicated dictionary is fenced exactly
        like a conf reconfiguration. Returns False when there is no
        mesh to shrink or only one device remains (the ladder then
        escalates to the whole-backend rungs)."""
        with self._lock:
            if self._mesh is None or not self._enabled:
                return False
            ids = [d.id for d in self._mesh.devices.flat]
            if len(ids) <= 1:
                return False
            victim = device_id if device_id in ids else ids[-1]
            self._excluded_ids = self._excluded_ids | {victim}
            self._degraded_reason = reason
            # force the next configure() to rebuild even under an
            # unchanged conf tuple
            self._config_key = None
            return True

    def exclude_devices(self, device_ids, reason: str) -> bool:
        """Evict a whole device GROUP from the mesh fault domain — the
        cluster layer's host-shrink rung (runtime/cluster.py): a lost
        HOST takes its entire dcn row of devices with it. Same
        contract as shrink_excluding: the exclusion folds into the
        config key, the next configure() rebuilds from the survivors
        (collapsing to a flat axis when the declared hierarchical
        shape no longer fits) and bumps the generation. Returns False
        when the eviction would leave no devices."""
        ids = frozenset(device_ids)
        if not ids:
            return False
        with self._lock:
            if self._mesh is None or not self._enabled:
                return False
            live = [d.id for d in self._mesh.devices.flat
                    if d.id not in ids]
            if not live:
                return False
            self._excluded_ids = self._excluded_ids | ids
            self._degraded_reason = reason
            self._config_key = None
            return True

    def restore(self, reason: str = "") -> bool:
        """Clear every ladder exclusion (the mesh returns to declared
        strength on the next configure()). Returns whether anything
        was excluded. The chaos harness probes this at end of run;
        a device that is genuinely still dead simply re-walks the
        ladder and gets excluded again."""
        with self._lock:
            had = bool(self._excluded_ids)
            self._excluded_ids = frozenset()
            self._degraded_reason = None
            if had:
                self._config_key = None
            return had

    def degraded_reason(self) -> Optional[str]:
        """Why the mesh runs below declared strength (None at full
        strength) — the explain()/health() surfacing hook."""
        with self._lock:
            return self._degraded_reason

    def health_snapshot(self) -> dict:
        """The mesh fault-domain state QueryService.health() reports."""
        with self._lock:
            return self._health_snapshot_locked()

    def _health_snapshot_locked(self) -> dict:
        """Snapshot body for callers already holding ``self._lock``
        (the shared-topology path in runtime/health.py)."""
        shape = ("x".join(str(d) for d in self._dims)
                 if self._enabled and self._mesh is not None else None)
        return {
            "enabled": self._enabled and self._mesh is not None,
            "shape": shape,
            "declaredShape": self._declared_shape,
            "excludedDeviceIds": sorted(self._excluded_ids),
            "degradedReason": self._degraded_reason,
            "generation": self._generation,
        }

    # -- state ---------------------------------------------------------------
    @property
    def enabled(self) -> bool:
        if _SUPPRESS.get() is not None:
            return False  # this attempt lands single-device
        with self._lock:
            return self._enabled and self._mesh is not None

    def mesh(self):
        with self._lock:
            return self._mesh

    @property
    def ndev(self) -> int:
        with self._lock:
            if self._mesh is None:
                return 0
            n = 1
            for d in self._dims:
                n *= d
            return n

    def effective_ndev(self) -> Optional[int]:
        """Mesh device count read under ONE lock hold — None when mesh-
        native execution is off. The enabled/ndev pair must be a single
        snapshot: two separate locked reads racing a concurrent
        reconfiguration can observe enabled=True then ndev=0 (the
        scan_placement atomicity argument, applied to the exchange's
        demotion check)."""
        if _SUPPRESS.get() is not None:
            return None
        with self._lock:
            if not self._enabled or self._mesh is None:
                return None
            n = 1
            for d in self._dims:
                n *= d
            return n

    def row_axes(self) -> Tuple[str, ...]:
        """The axes a row-sharded table partitions over — the flat axis
        of a 1-D mesh, or both axes of the hierarchical (dcn, ici) one
        (rows stripe the whole pod; collectives still address each axis
        independently)."""
        with self._lock:
            return self._axes

    def shape_str(self) -> Optional[str]:
        """Human/event-log mesh shape ('8' or '2x4'); None when off."""
        if _SUPPRESS.get() is not None:
            return None
        with self._lock:
            if not self._enabled or self._mesh is None:
                return None
            return "x".join(str(d) for d in self._dims)

    def generation(self) -> int:
        """Coherency counter: bumps on every effective reconfiguration.
        Folded into the executable cache's generation token, so a tree
        checked out under one mesh can neither serve nor re-park under
        another."""
        with self._lock:
            return self._generation

    def identity_token(self) -> str:
        """Stable token of the CURRENT mesh identity (enabled, dims,
        axes, device ids) — folded into the plan fingerprint so cached
        plans never cross mesh configs. A ladder-suppressed attempt
        gets its own token: its single-device tree must not collide
        with mesh-native variants of the same template."""
        if _SUPPRESS.get() is not None:
            return "mesh:suppressed"
        with self._lock:
            if not self._enabled or self._mesh is None:
                return "mesh:off"
            ids = ",".join(str(d.id) for d in self._mesh.devices.flat)
            return (f"mesh:{'x'.join(map(str, self._dims))}/"
                    f"{'+'.join(self._axes)}/{ids}")

    # -- sharding ------------------------------------------------------------
    def row_sharding(self):
        """NamedSharding partitioning the row axis across the mesh —
        THE plan-carried table sharding descriptor."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        with self._lock:
            if self._mesh is None:
                return None
            spec = P(self._axes if len(self._axes) > 1 else self._axes[0])
            return NamedSharding(self._mesh, spec)

    def scan_placement(self):
        """(row sharding, generation) read under ONE lock hold — the
        scan device-cache pairs the sharding it lands under with the
        token it caches under, and two separate locked reads could pair
        an old mesh's sharding with a post-reconfiguration token,
        serving that stale placement on every later cache hit.
        ``(None, None)`` when mesh-native execution is off."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        if _SUPPRESS.get() is not None:
            return None, None
        with self._lock:
            if not self._enabled or self._mesh is None:
                return None, None
            spec = P(self._axes if len(self._axes) > 1 else self._axes[0])
            return NamedSharding(self._mesh, spec), self._generation

    def replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        with self._lock:
            if self._mesh is None:
                return None
            return NamedSharding(self._mesh, P())

    def exchange_mesh(self, nparts: int):
        """(mesh, axis-or-axes) for an nparts-way all-to-all. The full
        runtime mesh when nparts covers it (a 2-D mesh exchanges over
        BOTH axes — partition id = flat device index, the all-to-all
        rides ici within each dcn group); a leading 1-D submesh when the
        exchange is narrower than the pod."""
        import jax
        from jax.sharding import Mesh
        with self._lock:
            mesh = self._mesh
            dims, axes = self._dims, self._axes
        if mesh is not None:
            total = 1
            for d in dims:
                total *= d
            if nparts == total:
                return mesh, (axes if len(axes) > 1 else axes[0])
            if nparts < total:
                flat = list(mesh.devices.flat)[:nparts]
                return Mesh(np.array(flat), ("data",)), "data"
        return Mesh(np.array(jax.devices()[:nparts]), ("data",)), "data"

#: THE process-wide mesh runtime (device topology is process state, like
#: the device manager that owns it)
MESH = MeshRuntime()


def count_mesh_upload(n: int = 1) -> None:
    """Record ``n`` host->device transfers on the mesh dispatch path —
    the warm-path contract is that this stays 0 between exchanges."""
    if n > 0:
        MESH_SCOPE.add("meshHostUploads", n)


def shard_put(arr, sharding):
    """Land one array onto the mesh under ``sharding`` — per-shard
    device transfers for host arrays (no single-device concat), a
    device-side reshard for arrays already resident. Host uploads are
    counted (the warm path must not pay any). THE shard-landing fault
    point: crash exercises the query-replay path, device_lost the
    partial-loss degradation ladder (runtime/health.py)."""
    import jax

    from spark_rapids_tpu.runtime.faults import fault_point
    fault_point("mesh.shard.put")
    if not isinstance(arr, jax.Array):
        count_mesh_upload(1)
    return jax.device_put(arr, sharding)


def ensure_host_devices(n_devices: int) -> int:
    """Force an ``n_devices``-wide virtual host-platform backend BEFORE
    the JAX backend initializes — the shared bootstrap of the multichip
    dryrun (``__graft_entry__.dryrun_multichip``) and the mesh harness
    (``scale_test --mesh``): bumps ``--xla_force_host_platform_device_count``
    in ``XLA_FLAGS`` (never shrinking an existing setting) and pins the
    cpu platform so one process models an N-chip pod. Real pods bring
    their own devices: ``SPARK_RAPIDS_TPU_DRYRUN_REAL=1`` skips the
    forcing entirely. Returns the live device count; callers decide how
    to fail when it is short (the flag cannot take effect if the
    backend initialized before this ran). Importing this module is
    deliberately backend-init-safe, so callers may import first and
    bootstrap after."""
    import os
    import re
    if os.environ.get("SPARK_RAPIDS_TPU_DRYRUN_REAL", "") != "1":
        want = max(n_devices, 8)
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      flags)
        if m is None:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={want}")
        elif int(m.group(1)) < want:
            os.environ["XLA_FLAGS"] = flags.replace(
                m.group(0), f"--xla_force_host_platform_device_count={want}")
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        # site packages may pin JAX_PLATFORMS at interpreter start; the
        # config update overrides it even when jax is already imported
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax
    return len(jax.devices())


def mesh_gather(value, rows: Optional[int] = None):
    """THE sanctioned mesh->host materialization point (RL-MESH-HOST):
    fetches a device value to host and counts the gathered elements.
    Every ICI exchange routes its per-partition live-count fetch
    through here; any future mesh-code host gather must too (the lint
    rule flags direct fetches). ``rows`` overrides the counted element
    number for fetches that carry validation overhead alongside the
    payload (a checksummed counts fetch counts its counts, not its
    digest word; a pure digest-pair compare counts 0) so
    meshGatherRows keeps meaning 'elements gathered', comparable
    across artifact rounds."""
    from spark_rapids_tpu.dispatch import host_fetch
    arr = np.asarray(host_fetch(value))
    if rows is None:
        rows = int(arr.shape[0]) if arr.ndim else 1
    if rows:
        MESH_SCOPE.add("meshGatherRows", rows)
    return arr


def wordsum_u32(a):
    """Order-independent uint32 word-sum digest of one device array —
    THE checksum both sides of a verified mesh gather compute (the
    TPAK-v2 frame CRC lifted to device buffers): bitcast every element
    to 32-bit words and wrap-sum them. Integer addition is associative
    and commutative, so a GSPMD-partitioned sum over mesh shards
    equals the single-device sum bit for bit — the digest is layout-
    independent by construction. Runs eagerly/inside jit; host code
    recomputes the same value with numpy views."""
    import jax
    import jax.numpy as jnp
    if a.dtype == jnp.bool_:
        return jnp.sum(a.astype(jnp.uint32), dtype=jnp.uint32)
    if a.dtype in (jnp.int8, jnp.int16):
        a = a.astype(jnp.int32)
    return jnp.sum(jax.lax.bitcast_convert_type(a, jnp.uint32),
                   dtype=jnp.uint32)
