"""Bloom-filter runtime join filtering (reference: SURVEY.md §2.9 JNI
BloomFilter; Spark's InjectRuntimeFilter plans BloomFilterAggregate on the
build side and BloomFilterMightContain on the probe side of selective
joins — sql-plugin shims GpuBloomFilterAggregate / GpuBloomFilterMightContain).

TPU-first representation: the filter is a device BOOL array of ``num_bits``
slots (XLA scatters/gathers booleans natively; a packed word layout would
only add emulated shift chains). k bit indexes derive from one xxhash64
per value via Spark's h1 + i*h2 double-hashing over the 64-bit hash's
halves. Building is one scatter-max over the build keys; membership is k
gathers ANDed — both fuse into surrounding programs.

Surface: ``build_bloom_filter(df, column)`` aggregates a DataFrame's
column into a BloomFilter handle (the BloomFilterAggregate analog), and
``F.might_contain(bloom, expr)`` is the probe-side expression. Note on
profitability: with static-shape kernels a bloom pre-filter does not
shrink per-operator compute (buckets stay capacity-sized); it pays where
row COUNTS matter — before a shuffle exchange or to cut matched output
rows — which is why it is an explicit tool, not an unconditional rewrite."""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.expr import (
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
)

DEFAULT_NUM_BITS = 1 << 20
DEFAULT_NUM_HASHES = 3


def _hash_split(h):
    h = h.astype(jnp.uint64)
    h1 = (h & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    h2 = (h >> jnp.uint64(32)).astype(jnp.uint32)
    return h1, h2


def _bit_indexes_dev(data, num_bits: int, k: int) -> List[jax.Array]:
    from spark_rapids_tpu.ops.hashfns import xxhash64_device
    h = xxhash64_device([(data.astype(jnp.int64),
                          jnp.ones(data.shape[0], jnp.bool_), T.LONG)])
    h1, h2 = _hash_split(h)
    nb = jnp.uint32(num_bits)
    return [((h1 + jnp.uint32(i) * h2) % nb).astype(jnp.int32)
            for i in range(k)]


class BloomFilter:
    """Device-resident filter handle (the materialized
    BloomFilterAggregate result). ``host_bits`` backs the might_contain
    expression's aux input so compiled traces are SHARED across filters
    of the same shape (the device copy is content-interned by
    dispatch.device_const)."""

    def __init__(self, bits: jax.Array, num_hashes: int):
        from spark_rapids_tpu.dispatch import host_fetch
        self.bits = bits
        self.num_bits = int(bits.shape[0])
        self.num_hashes = int(num_hashes)
        self.host_bits = np.asarray(host_fetch(bits))

    def approx_set_bits(self) -> int:
        from spark_rapids_tpu.dispatch import host_fetch
        return int(host_fetch(jnp.sum(self.bits.astype(jnp.int32))))


_BUILD_CACHE = {}


def _build_kernel(num_bits: int, k: int, cap: int):
    key = (num_bits, k, cap)
    fn = _BUILD_CACHE.get(key)
    if fn is None:
        from spark_rapids_tpu.dispatch import tpu_jit

        def build(data, valid):
            bits = jnp.zeros(num_bits, jnp.bool_)
            for idx in _bit_indexes_dev(data, num_bits, k):
                tgt = jnp.where(valid, idx, num_bits)
                bits = bits.at[tgt].max(True, mode="drop")
            return bits

        fn = tpu_jit(build)
        _BUILD_CACHE[key] = fn
    return fn


def build_bloom_filter(df, column: str,
                       num_bits: int = None,
                       num_hashes: int = None) -> BloomFilter:
    """Aggregate ``df[column]`` (integral type) into a BloomFilter — the
    engine's bloom_filter_agg. Executes the DataFrame's plan on device and
    folds every batch into one bit array."""
    if num_bits is None or num_hashes is None:
        from spark_rapids_tpu.conf import (
            BLOOM_DEFAULT_NUM_BITS,
            BLOOM_DEFAULT_NUM_HASHES,
        )
        conf = getattr(df.session, "conf", None)
        if num_bits is None:
            num_bits = (conf.get_entry(BLOOM_DEFAULT_NUM_BITS)
                        if conf else DEFAULT_NUM_BITS)
        if num_hashes is None:
            num_hashes = (conf.get_entry(BLOOM_DEFAULT_NUM_HASHES)
                          if conf else DEFAULT_NUM_HASHES)
    schema = dict(df.select(column).plan.output_schema())
    if not isinstance(schema[column], T.IntegralType):
        raise ColumnarProcessingError(
            f"bloom filter column {column} must be integral, got "
            f"{schema[column].simple_string()}")
    cols, _nrows = df.select(column).to_device_arrays()
    data, valid = cols[column][0], cols[column][1]
    fn = _build_kernel(num_bits, num_hashes, int(data.shape[0]))
    return BloomFilter(fn(data, valid), num_hashes)


class BloomFilterMightContain(Expression):
    """might_contain(bloom, e) — True when e MAY be in the build set (no
    false negatives), null for null input. The bit array rides as a
    device-resident constant captured per plan (the reference ships the
    serialized bloom as a GpuLiteral into the probe-side expression)."""

    def __init__(self, bloom: BloomFilter, child: Expression):
        self.bloom = bloom
        self.children = (child,)

    @property
    def data_type(self):
        return T.BOOLEAN

    def key(self):
        # content-independent: the bit array rides as an aux input, so
        # every bloom of the same shape shares one compiled trace
        return ("mightcontain", self.bloom.num_bits,
                self.bloom.num_hashes, self.children[0].key())

    def prep(self, pctx, child_preps):
        return NodePrep(
            aux_slots=(pctx.add_aux(self.bloom.host_bits),))

    def with_children(self, children):
        return BloomFilterMightContain(self.bloom, children[0])

    @property
    def device_supported(self):
        return isinstance(self.children[0].data_type, T.IntegralType)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.children[0].eval_cpu(table)
        # the host copy is cached at filter build; re-fetching the full
        # bits array per batch would stall the pipeline ~0.1s each
        bits = self.bloom.host_bits
        from spark_rapids_tpu.ops.hashfns import xxhash64_host
        n = len(c)
        out = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            if not c.validity[i]:
                continue
            h = xxhash64_host(
                [(int(c.data[i]), True, T.LONG)]) & 0xFFFFFFFFFFFFFFFF
            h1 = h & 0xFFFFFFFF
            h2 = h >> 32
            hit = True
            for j in range(self.bloom.num_hashes):
                ix = ((h1 + j * h2) & 0xFFFFFFFF) % self.bloom.num_bits
                if not bits[ix]:
                    hit = False
                    break
            out[i] = hit
        return HostColumn(T.BOOLEAN, out, c.validity.copy())

    def eval_dev(self, ctx: EvalCtx, child_vals, prep) -> DevVal:
        (c,) = child_vals
        bits = ctx.aux[prep.aux_slots[0]]
        hit = jnp.ones(ctx.capacity, jnp.bool_)
        for idx in _bit_indexes_dev(c.data, self.bloom.num_bits,
                                    self.bloom.num_hashes):
            hit = hit & bits[idx]
        return DevVal(hit, c.validity)
