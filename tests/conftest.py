"""Test fixtures. Runs JAX on a virtual 8-device CPU mesh so multi-chip
sharding logic is exercised without TPU hardware (the driver dry-runs the
real multi-chip path separately)."""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# the axon site package pins JAX_PLATFORMS=axon at interpreter start; the
# config update below overrides it reliably.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# run the static plan verifier in ERROR mode for every session the test
# suite creates (the spark-rapids `spark.rapids.sql.test.enabled`
# assert-on-fallback pattern): any structural invariant a converted plan
# violates fails the test that built it. Injected per-session rather
# than flipped in the conf REGISTRY so generated docs (CONFIGS.md drift
# tests) still show the production default.
from spark_rapids_tpu.session import TpuSession  # noqa: E402

_ORIG_SESSION_INIT = TpuSession.__init__


def _verifying_init(self, conf=None):
    conf = dict(conf or {})
    conf.setdefault("spark.rapids.sql.planVerify.mode", "error")
    _ORIG_SESSION_INIT(self, conf)


TpuSession.__init__ = _verifying_init

_TESTS_RUN = {"n": 0}


@pytest.fixture(autouse=True)
def _periodic_jax_cache_clear():
    """Free jitted XLA:CPU executables every few hundred tests. One
    full-suite process otherwise accumulates ~1k compiled programs;
    on some hosts XLA's CPU compiler segfaults once that much JIT
    state has piled up (observed at ~95% of the suite, always inside
    backend_compile). Recompiles cost a little time; crashes cost
    the whole run."""
    yield
    _TESTS_RUN["n"] += 1
    if _TESTS_RUN["n"] % 250 == 0:
        jax.clear_caches()


@pytest.fixture(scope="session")
def session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession()


@pytest.fixture(scope="session")
def cpu_session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.sql.enabled": "false"})
