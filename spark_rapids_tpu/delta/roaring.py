"""64-bit roaring bitmap array: the deletion-vector bitmap codec.

Reference (SURVEY.md §2.8): Delta Lake deletion vectors store deleted row
indexes as a ``RoaringBitmapArray`` (an array of 32-bit roaring bitmaps,
one per 2^32 range) in the portable serialization; the reference's scan
applies them on the GPU (deletion-vector scan support in the delta-lake
module). This module implements the portable 32-bit roaring container
format (array / bitmap / run containers) plus the 64-bit array wrapper,
both directions, in numpy — the TPU build's DV codec.

Format written (standard roaring portable, no-run flavor):
  [u32 cookie=12347][u32 n_containers]
  per container: [u16 key][u16 cardinality-1]
  offset header: [u32 byte-offset] per container
  containers: array (u16 values, card<=4096) or bitmap (8KiB bitset)
Read side additionally accepts run containers (cookie 12346 + run bitset).
"""

from __future__ import annotations

import struct
from typing import List

import numpy as np

from spark_rapids_tpu.errors import ColumnarProcessingError

SERIAL_COOKIE_NO_RUN = 12347
SERIAL_COOKIE_RUN = 12346
NO_OFFSET_THRESHOLD = 4
ARRAY_MAX_CARD = 4096
#: 64-bit wrapper magic for the DV blob (engine-native framing; one u64
#: bitmap count follows, then each 32-bit bitmap keyed by its high word)
MAGIC_64 = 1681511377


# -- 32-bit portable bitmap --------------------------------------------------

def serialize_bitmap32(values: np.ndarray) -> bytes:
    """values: sorted unique uint32 array -> portable roaring bytes."""
    values = np.asarray(values, dtype=np.uint32)
    keys = (values >> 16).astype(np.uint16)
    lows = (values & 0xFFFF).astype(np.uint16)
    uniq_keys, starts = np.unique(keys, return_index=True)
    n = len(uniq_keys)
    bounds = list(starts) + [len(values)]

    header = struct.pack("<II", SERIAL_COOKIE_NO_RUN, n)
    desc = bytearray()
    bodies: List[bytes] = []
    for i, k in enumerate(uniq_keys):
        chunk = lows[bounds[i]:bounds[i + 1]]
        card = len(chunk)
        desc += struct.pack("<HH", int(k), card - 1)
        if card <= ARRAY_MAX_CARD:
            bodies.append(chunk.astype("<u2").tobytes())
        else:
            bits = np.zeros(8192, dtype=np.uint8)
            idx = chunk.astype(np.uint32)
            np.bitwise_or.at(bits, idx >> 3,
                             (1 << (idx & 7)).astype(np.uint8))
            bodies.append(bits.tobytes())
    # offset header (always written in the no-run flavor)
    base = len(header) + len(desc) + 4 * n
    offsets = bytearray()
    pos = base
    for b in bodies:
        offsets += struct.pack("<I", pos)
        pos += len(b)
    return bytes(header) + bytes(desc) + bytes(offsets) + b"".join(bodies)


def deserialize_bitmap32(buf: bytes, pos: int = 0):
    """-> (sorted uint32 values, bytes consumed)."""
    start = pos
    (cookie,) = struct.unpack_from("<I", buf, pos)
    has_run = (cookie & 0xFFFF) == SERIAL_COOKIE_RUN
    if has_run:
        n = (cookie >> 16) + 1
        pos += 4
        run_flags = buf[pos:pos + (n + 7) // 8]
        pos += (n + 7) // 8
    elif cookie == SERIAL_COOKIE_NO_RUN:
        (n,) = struct.unpack_from("<I", buf, pos + 4)
        pos += 8
        run_flags = b"\x00" * ((n + 7) // 8)
    else:
        raise ColumnarProcessingError(
            f"bad roaring cookie {cookie}")
    keys = np.empty(n, dtype=np.uint32)
    cards = np.empty(n, dtype=np.int64)
    for i in range(n):
        k, c = struct.unpack_from("<HH", buf, pos)
        keys[i], cards[i] = k, c + 1
        pos += 4
    if not has_run or n >= NO_OFFSET_THRESHOLD:
        pos += 4 * n  # skip offset header (containers are sequential)
    out = []
    for i in range(n):
        is_run = bool(run_flags[i >> 3] & (1 << (i & 7)))
        if is_run:
            (n_runs,) = struct.unpack_from("<H", buf, pos)
            pos += 2
            vals = []
            for _ in range(n_runs):
                s, ln = struct.unpack_from("<HH", buf, pos)
                pos += 4
                vals.append(np.arange(s, s + ln + 1, dtype=np.uint32))
            chunk = np.concatenate(vals) if vals else \
                np.empty(0, dtype=np.uint32)
        elif cards[i] <= ARRAY_MAX_CARD:
            chunk = np.frombuffer(buf, dtype="<u2", count=cards[i],
                                  offset=pos).astype(np.uint32)
            pos += 2 * cards[i]
        else:
            bits = np.frombuffer(buf, dtype=np.uint8, count=8192,
                                 offset=pos)
            pos += 8192
            chunk = np.flatnonzero(
                np.unpackbits(bits, bitorder="little")).astype(np.uint32)
        out.append(chunk + (keys[i] << 16))
    values = (np.concatenate(out) if out else np.empty(0, dtype=np.uint32))
    return values, pos - start


# -- 64-bit array wrapper ----------------------------------------------------

def serialize_dv(row_indexes: np.ndarray) -> bytes:
    """Sorted unique int64 deleted-row indexes -> DV blob."""
    v = np.unique(np.asarray(row_indexes, dtype=np.uint64))
    highs = (v >> np.uint64(32)).astype(np.uint32)
    uniq, starts = np.unique(highs, return_index=True)
    bounds = list(starts) + [len(v)]
    out = bytearray(struct.pack("<IQ", MAGIC_64, len(uniq)))
    for i, h in enumerate(uniq):
        lows = (v[bounds[i]:bounds[i + 1]] & np.uint64(0xFFFFFFFF)).astype(
            np.uint32)
        out += struct.pack("<I", int(h))
        out += serialize_bitmap32(lows)
    return bytes(out)


def deserialize_dv(buf: bytes) -> np.ndarray:
    magic, n = struct.unpack_from("<IQ", buf, 0)
    if magic != MAGIC_64:
        raise ColumnarProcessingError(f"bad deletion-vector magic {magic}")
    pos = 12
    parts = []
    for _ in range(n):
        (high,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        lows, used = deserialize_bitmap32(buf, pos)
        pos += used
        parts.append(lows.astype(np.uint64) | (np.uint64(high) << np.uint64(32)))
    return (np.concatenate(parts) if parts
            else np.empty(0, dtype=np.uint64)).astype(np.int64)
