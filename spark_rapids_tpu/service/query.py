"""Query lifecycle: handle state machine + cooperative cancellation.

Reference: Spark's ``SparkContext.cancelJobGroup`` / task kill flag —
the reference plugin inherits task interruption from Spark's executor
(``TaskContext.isInterrupted`` checked between columnar batches). This
engine's analog: every submitted query gets a :class:`QueryHandle`
whose ``cancel()`` (and the scheduler's deadline sweep) sets a flag
that :func:`install_cancellation` checks at EVERY exec boundary batch
pull, so a long plan stops between batches instead of after the query.

:func:`install_cancellation` is the third per-query exec-boundary
wrapper in the ``install_fault_boundaries`` (runtime/faults.py) /
``install_observation`` (obs/spans.py) family, installed OUTERMOST by
``TpuSession._plan_and_drain`` when a cancel scope is active on the
executing thread.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Optional

from spark_rapids_tpu.errors import QueryCancelledError, QueryTimeoutError
from spark_rapids_tpu.lockorder import ordered_lock


class QueryState:
    """Lifecycle states (string constants; the handle's ``state``)."""

    QUEUED = "QUEUED"        # admitted to a pool queue, waiting
    ADMITTED = "ADMITTED"    # popped by a worker, about to run
    RUNNING = "RUNNING"      # executing on a worker thread
    FINISHED = "FINISHED"    # result available
    FAILED = "FAILED"        # raised a non-cancellation error
    CANCELLED = "CANCELLED"  # cancel() won the race
    TIMED_OUT = "TIMED_OUT"  # deadline expired (queued or running)

    TERMINAL = frozenset((FINISHED, FAILED, CANCELLED, TIMED_OUT))


class CancelScope:
    """The cooperative-interruption contract between a handle and the
    exec boundary: ``check()`` raises the typed interruption when the
    query was cancelled or its deadline passed. Deadlines are monotonic
    (time.monotonic) so wall-clock steps can't fire them."""

    __slots__ = ("cancelled", "deadline", "checks")

    def __init__(self, deadline: Optional[float] = None):
        self.cancelled = threading.Event()
        self.deadline = deadline
        self.checks = 0

    def cancel(self) -> None:
        self.cancelled.set()

    def expired(self) -> bool:
        return self.deadline is not None and time.monotonic() > self.deadline

    def check(self) -> None:
        self.checks += 1
        if self.cancelled.is_set():
            raise QueryCancelledError("query cancelled")
        if self.expired():
            raise QueryTimeoutError(
                "query deadline expired while running")


#: the executing thread's active cancel scope (contextvar like the
#: masked-batch / retry knobs: set by the service worker around
#: session.execute, read by _plan_and_drain to install the boundary)
_SCOPE: contextvars.ContextVar[Optional[CancelScope]] = \
    contextvars.ContextVar("rapids_cancel_scope", default=None)


def current_cancel_scope() -> Optional[CancelScope]:
    return _SCOPE.get()


class cancel_scope:
    """``with cancel_scope(scope): session.execute(...)``."""

    def __init__(self, scope: CancelScope):
        self.scope = scope
        self._token = None

    def __enter__(self) -> CancelScope:
        self._token = _SCOPE.set(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _SCOPE.reset(self._token)
        return False


def _cancellable(fn):
    """The boundary reads the ACTIVE scope from the contextvar at every
    pull instead of closing over one: executable trees are cached and
    reused across queries (plan/executable_cache.py), so a wrapper
    installed for query A must check query B's scope when B reuses the
    tree — and must check nothing at all for a query running without a
    scope (a stale closed-over scope whose deadline passed would time
    out every future reuse)."""
    def wrapped(*args, **kwargs):
        scope = _SCOPE.get()
        if scope is not None:
            scope.check()
        it = fn(*args, **kwargs)
        while True:
            scope = _SCOPE.get()
            if scope is not None:
                scope.check()   # between batches: the cooperative point
            try:
                batch = next(it)
            except StopIteration:
                return
            yield batch

    return wrapped


def install_cancellation(executable,
                         scope: Optional[CancelScope] = None) -> None:
    """Wrap every device exec's execute()/execute_masked() (and the
    DeviceToHost root's execute_cpu) with a pre-pull check of the
    executing thread's ACTIVE cancel scope (``scope`` is accepted for
    call-site compatibility but the wrapper always resolves the scope
    dynamically — see _cancellable). Installed per query AFTER fault
    guards and observation, so a cancellation raise is never
    misattributed as an operator crash and never counted as operator
    time. Idempotent per exec instance."""
    from spark_rapids_tpu.execs.base import DeviceToHost, TpuExec
    from spark_rapids_tpu.lore import _iter_tree
    for e in _iter_tree(executable):
        if getattr(e, "_cancel_installed", False):
            continue
        if isinstance(e, TpuExec):
            e._cancel_installed = True
            e.execute = _cancellable(e.execute)
            e.execute_masked = _cancellable(e.execute_masked)
        elif isinstance(e, DeviceToHost):
            e._cancel_installed = True
            e.execute_cpu = _cancellable(e.execute_cpu)


class QueryHandle:
    """One submitted query. Callers hold this to wait, inspect, or
    cancel; the scheduler drives the state machine. All transitions go
    through :meth:`_transition` under the handle's lock and terminal
    states latch (a cancel racing a finish cannot un-finish it)."""

    _seq_lock = ordered_lock("service.handle.seq")
    _seq = 0

    def __init__(self, *, tenant: str, pool: str, tag: Optional[str],
                 sql_text: Optional[str], plan,
                 deadline: Optional[float]):
        with QueryHandle._seq_lock:
            QueryHandle._seq += 1
            self.query_id = QueryHandle._seq
        self.tenant = tenant
        self.pool = pool
        self.tag = tag
        self.sql_text = sql_text
        self.plan = plan
        self.scope = CancelScope(deadline)
        self._lock = ordered_lock("service.handle")
        self._done = threading.Event()
        self._state = QueryState.QUEUED
        self.submit_t = time.monotonic()
        self.start_t: Optional[float] = None
        self.end_t: Optional[float] = None
        self.result_table = None
        self.error: Optional[BaseException] = None
        self.cache_hit = False
        self.queue_wait_s: Optional[float] = None
        self.event_record: Optional[dict] = None
        #: literal-stripped structural fingerprint — the quarantine key
        #: (computed LAZILY by the scheduler: only when the quarantine
        #: ledger has strikes to check against, or at strike time —
        #: the clean-process submit path never pays the plan walk).
        #: None can mean "not computed yet" (_template_fp_done False)
        #: or "unfingerprintable plan" (True)
        self.template_fp: Optional[str] = None
        self._template_fp_done = False
        #: times the scheduler put this handle BACK in its queue after
        #: its worker or the device died under it (survivability replay)
        self.requeues = 0
        #: set by the scheduler so cancel() can pull a QUEUED handle out
        self._service = None

    # -- state machine ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def _transition(self, new_state: str, *, error=None, result=None) -> bool:
        """Move to ``new_state``; returns False when already terminal
        (the transition lost a race and must not apply)."""
        with self._lock:
            if self._state in QueryState.TERMINAL:
                return False
            self._state = new_state
            if new_state == QueryState.RUNNING:
                self.start_t = time.monotonic()
                self.queue_wait_s = self.start_t - self.submit_t
            if new_state in QueryState.TERMINAL:
                self.end_t = time.monotonic()
                self.error = error
                if result is not None:
                    self.result_table = result
        if new_state in QueryState.TERMINAL:
            self._done.set()
        return True

    # -- caller surface -----------------------------------------------------
    def cancel(self) -> bool:
        """Request cancellation. A QUEUED query transitions immediately
        (it never runs); a RUNNING one is interrupted cooperatively at
        the next exec boundary. Returns False when already terminal."""
        self.scope.cancel()
        svc = self._service
        if svc is not None and svc._remove_queued(self):
            done = self._transition(
                QueryState.CANCELLED,
                error=QueryCancelledError("cancelled while queued"))
            if done:
                svc._count_event("cancelled")
            return done
        with self._lock:
            return self._state not in QueryState.TERMINAL

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        """Block for the result HostTable; raises the query's error for
        FAILED/CANCELLED/TIMED_OUT terminal states."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} still {self.state} after "
                f"{timeout}s wait")
        if self.error is not None:
            raise self.error
        return self.result_table

    @property
    def latency_s(self) -> Optional[float]:
        """submit -> terminal wall time (queue wait included)."""
        if self.end_t is None:
            return None
        return self.end_t - self.submit_t

    @property
    def run_s(self) -> Optional[float]:
        """RUNNING -> terminal wall time (None when never ran)."""
        if self.end_t is None or self.start_t is None:
            return None
        return self.end_t - self.start_t

    def __repr__(self):
        return (f"QueryHandle(id={self.query_id}, tenant={self.tenant!r}, "
                f"pool={self.pool!r}, state={self.state})")
