"""Python-AST repo lint: project invariants the type system can't hold.

The TPU-first rule this codebase lives by (dispatch.py header): NOTHING
transfers host<->device on a warm query outside the sanctioned sites.
The type checker cannot see a stray ``jax.device_get`` in a kernel or a
conf key referenced by a typo'd string — this lint can.  Rules (RL-*):

* RL-HOST-SYNC — no host synchronization (``jax.device_get``,
  ``.block_until_ready()``) inside execs/ or ops/ hot paths except via
  the sanctioned ``dispatch.host_fetch`` helper.
* RL-JNP-SCOPE — ``jax.numpy`` imports only in the device layers.
* RL-CONF-KEY — every ``spark.*`` conf key referenced as a string
  literal must be declared in the conf registry.
* RL-NONDETERMINISM — no wall-clock or unseeded randomness in kernel
  modules (results must replay bit-identically; LORE depends on it).
* RL-DEAD-LAMBDA — a lambda bound to a name that is never referenced
  again is dead code.
* RL-FAULT-POINT — the chaos harness's fault-point registry
  (runtime/faults.FAULT_POINTS) and the ``fault_point("<name>")`` call
  sites must agree in both directions: every registered point names an
  existing site in its registered module, every site uses a registered
  name, and names are string literals (a computed name would dodge the
  audit).
* RL-THREAD-SHARED — the query service executes queries from a worker
  pool, so runtime/, shuffle/ and service/ modules are concurrent by
  contract: module-global mutable containers (and class-level singleton
  slots) written inside a function must be written under a lock guard
  (a ``with <something named *lock*/*cond*>:`` block) or appear in the
  sanctioned allowlist with a justification.
* RL-MESH-HOST — mesh-native execution keeps shards device-resident
  BETWEEN exchanges (the PERF.md upload cost class this PR removes):
  inside ``parallel/`` and the shard-dispatch placement layer, host
  materialization (``np.asarray``, ``jax.device_get``, ``host_fetch``,
  ``.block_until_ready()``, ``.addressable_shards`` reads) may appear
  only at sanctioned gather points (``_MESH_HOST_ALLOWLIST``, each
  entry justified).
* RL-WRITE-COMMIT — the exactly-once write contract holds only if
  every byte of table output stages through the transactional
  committer (io/committer.py): in ``io/`` modules, file-creating calls
  (write-mode ``open``, ``*.write_table``, ``*.write_csv``) may appear
  only inside the ``_write_one`` staged-path callbacks, and
  ``os.replace``/``os.rename`` promotion belongs to the committer
  alone. ``committer.py`` itself and ``filecache.py`` (cache files are
  not table output) are exempt.
* RL-KERNEL-HOST — the Pallas kernel layer (``kernels/``) is pure
  device code that executes INSIDE other traces: any numpy
  materialization (``import numpy`` at all) or host synchronization
  (``jax.device_get``, ``host_fetch``, ``.block_until_ready()``)
  there would stall the trace or smuggle device data to the host
  mid-kernel. Sanctioned exceptions go in ``_KERNEL_HOST_ALLOWLIST``
  with a justification (same hook shape as RL-MESH-HOST).
* RL-OBS-PASSIVE — the telemetry sampler (``obs/telemetry.py``) runs
  on a background thread BETWEEN queries by design: it may not touch
  the device (no jax/jnp at all, no host syncs, no
  ``finalize_observation`` — that forces the deferred row-count
  fetch), may not drive query execution (``execute``/``collect*``),
  and may not take the query-path locks (the device semaphore, the
  scheduler condition, the session obs lock) — sampling must never
  perturb the execution it observes. Sanctioned exceptions go in
  ``_OBS_PASSIVE_ALLOWLIST`` with a justification.
* RL-MEM-ACCOUNT — the device memory budget (runtime/memory.py
  MemoryArbiter) only holds if every device landing is ACCOUNTED:
  inside ``execs/`` and ``ops/``, raw ``jax.device_put`` calls are
  forbidden — landings route through ``DeviceTable.from_host`` (which
  reserves against the budget and accounts the landed bytes) or
  appear in ``_MEM_ACCOUNT_ALLOWLIST`` with a justification (tiny
  non-table transfers like digest scalars).
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make

#: directories (under spark_rapids_tpu/) whose modules are device layers
#: and may import jax.numpy
_DEVICE_DIRS = ("execs", "ops", "columnar", "parallel", "runtime",
                "shuffle", "shims", "models", "kernels")
#: top-level device-layer files
_DEVICE_FILES = ("dispatch.py", "udf.py")

#: np.random attributes that construct SEEDED generators (allowed in
#: kernels); everything else on np.random is process-global state
_SEEDED_RANDOM_OK = {"default_rng", "Generator", "SeedSequence",
                     "BitGenerator", "PCG64", "Philox"}

_CONF_KEY_RE = re.compile(r"^spark\.(rapids|sql)\.[A-Za-z0-9_]"
                          r"[A-Za-z0-9_.]*[A-Za-z0-9_]$")


def _repo_root(repo_root: Optional[str]) -> str:
    if repo_root:
        return repo_root
    import spark_rapids_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(spark_rapids_tpu.__file__)))


def _iter_source_files(root: str):
    pkg = os.path.join(root, "spark_rapids_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)
    for f in ("bench.py", "scale_test.py"):
        p = os.path.join(root, f)
        if os.path.exists(p):
            yield p


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root)


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute/name chain ('' when not a plain chain)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ---------------------------------------------------------------------------
# per-rule visitors
# ---------------------------------------------------------------------------


def _is_device_expr(node: ast.AST) -> bool:
    """Is this expression PROVABLY a device value — a jnp./jax. call not
    already funneled through the sanctioned host_fetch wrapper (whose
    RESULT is host data, however device-y its argument)?"""
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain == "host_fetch" or chain.endswith(".host_fetch"):
            return False
        if chain.startswith(("jnp.", "jax.")):
            return True
    for child in ast.iter_child_nodes(node):
        if _is_device_expr(child):
            return True
    return False


def _check_host_sync(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    in_hot_path = rel.startswith(("spark_rapids_tpu/execs/",
                                  "spark_rapids_tpu/ops/"))
    if not in_hot_path:
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            # `from jax import device_get` would make the call below
            # invisible to the chain matcher — ban the import form too
            for a in node.names:
                if a.name in ("device_get", "block_until_ready"):
                    diags.append(make(
                        "RL-HOST-SYNC", f"{rel}:{node.lineno}",
                        f"importing jax.{a.name} into a hot path; route "
                        "through dispatch.host_fetch so syncs are "
                        "counted and reviewable"))
            continue
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain.endswith(".block_until_ready"):
            diags.append(make(
                "RL-HOST-SYNC", f"{rel}:{node.lineno}",
                "block_until_ready() stalls the dispatch pipeline; use "
                "dispatch.host_fetch at a sanctioned sync point"))
        elif chain == "jax.device_get" or chain.endswith(".device_get") \
                or chain == "device_get":
            diags.append(make(
                "RL-HOST-SYNC", f"{rel}:{node.lineno}",
                "raw jax.device_get in a hot path (~0.1s tunnel stall "
                "each); route through dispatch.host_fetch so syncs are "
                "counted and reviewable"))
        elif chain in ("np.asarray", "numpy.asarray", "float", "int") \
                and node.args and _is_device_expr(node.args[0]):
            # the statically-decidable slice of "np.asarray/float/int on
            # device values": the argument is itself a jnp./jax. call,
            # so the conversion provably forces a device sync (general
            # deviceness needs dataflow a lint can't do)
            diags.append(make(
                "RL-HOST-SYNC", f"{rel}:{node.lineno}",
                f"{chain}() over a jax expression synchronizes the "
                "device; route through dispatch.host_fetch"))


def _check_jnp_scope(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    parts = rel.split("/")
    allowed = False
    if parts[0] != "spark_rapids_tpu":
        allowed = False  # bench.py / scale_test.py are host drivers
    elif len(parts) == 2:
        allowed = parts[1] in _DEVICE_FILES
    else:
        allowed = parts[1] in _DEVICE_DIRS
    if allowed:
        return
    for node in ast.walk(tree):
        hit = None
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy":
                    hit = f"{a.name} imported"
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax.numpy" or (
                    node.module == "jax"
                    and any(a.name == "numpy" for a in node.names)):
                hit = "jax.numpy imported"
        elif isinstance(node, ast.Attribute):
            # `import jax; jax.numpy.foo(...)` bypasses the import
            # check — catch the attribute access form too (exact match:
            # the inner `jax.numpy` node; avoids double-reporting the
            # enclosing `jax.numpy.foo` chain)
            if _attr_chain(node) == "jax.numpy":
                hit = "jax.numpy used"
        if hit:
            diags.append(make(
                "RL-JNP-SCOPE", f"{rel}:{node.lineno}",
                f"{hit} outside the device layers "
                f"({', '.join(_DEVICE_DIRS)}); host-side layers must "
                "stay device-agnostic"))


def _check_conf_keys(rel: str, tree: ast.AST, declared,
                     diags: List[Diagnostic]):
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Constant)
                and isinstance(node.value, str)):
            continue
        v = node.value
        if not _CONF_KEY_RE.match(v):
            continue
        if v in declared:
            continue
        diags.append(make(
            "RL-CONF-KEY", f"{rel}:{node.lineno}",
            f"conf key {v!r} is not declared in the conf registry — "
            "typo, or a key removed without cleaning its references"))


def _check_nondeterminism(rel: str, tree: ast.AST,
                          diags: List[Diagnostic]):
    in_kernel = rel.startswith(("spark_rapids_tpu/execs/",
                                "spark_rapids_tpu/ops/"))
    if not in_kernel:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        bad = None
        if chain in ("time.time", "datetime.now", "datetime.datetime.now",
                     "date.today", "datetime.date.today",
                     "datetime.utcnow", "datetime.datetime.utcnow"):
            bad = f"{chain}() (wall clock)"
        else:
            parts = chain.split(".")
            if len(parts) >= 2 and parts[-2] == "random" and \
                    parts[0] in ("np", "numpy") and \
                    parts[-1] not in _SEEDED_RANDOM_OK:
                bad = f"{chain}() (process-global RNG state)"
            elif chain.startswith("random.") and len(parts) == 2:
                bad = f"{chain}() (unseeded stdlib RNG)"
        if bad:
            diags.append(make(
                "RL-NONDETERMINISM", f"{rel}:{node.lineno}",
                f"{bad} in a kernel module — results must replay "
                "bit-identically (seeded default_rng only)"))


def _is_fault_point_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = _attr_chain(node.func)
    return chain == "fault_point" or chain.endswith(".fault_point")


def _check_fault_sites(rel: str, tree: ast.AST, calls,
                       diags: List[Diagnostic]):
    """Per-file half of RL-FAULT-POINT: record every fault_point call
    into ``calls`` (name -> [file:line]) and flag non-literal or
    unregistered names at the site."""
    from spark_rapids_tpu.runtime.faults import FAULT_POINTS
    for node in ast.walk(tree):
        if not _is_fault_point_call(node):
            continue
        arg = node.args[0] if node.args else None
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            diags.append(make(
                "RL-FAULT-POINT", f"{rel}:{node.lineno}",
                "fault_point() name must be a string literal so the "
                "registry audit can see it"))
            continue
        name = arg.value
        if name not in FAULT_POINTS:
            diags.append(make(
                "RL-FAULT-POINT", f"{rel}:{node.lineno}",
                f"fault_point({name!r}) is not registered in "
                "runtime/faults.FAULT_POINTS"))
            continue
        calls.setdefault(name, []).append(f"{rel}:{node.lineno}")


def _check_fault_registry(calls, diags: List[Diagnostic]):
    """Cross-file half of RL-FAULT-POINT: every registered point must
    name at least one existing call site, and a site must live in the
    module the registry claims hosts it (stale registry entries would
    otherwise advertise injectable faults that never fire)."""
    from spark_rapids_tpu.runtime.faults import FAULT_POINTS
    for name, (module, _doc) in sorted(FAULT_POINTS.items()):
        sites = calls.get(name, [])
        if not sites:
            diags.append(make(
                "RL-FAULT-POINT", f"faults.FAULT_POINTS[{name!r}]",
                f"registered fault point has no fault_point({name!r}) "
                "call site anywhere in the repo"))
        elif not any(s.rsplit(":", 1)[0] == module for s in sites):
            diags.append(make(
                "RL-FAULT-POINT", f"faults.FAULT_POINTS[{name!r}]",
                f"no call site in the registered module {module} "
                f"(found: {', '.join(sites)})"))


#: directories whose modules must be thread-safe (the query service's
#: worker pool runs through all three concurrently)
_THREAD_SHARED_DIRS = ("spark_rapids_tpu/runtime/",
                       "spark_rapids_tpu/shuffle/",
                       "spark_rapids_tpu/service/",
                       "spark_rapids_tpu/streaming/")

#: sanctioned unlocked writes: "file:name" -> why the pattern is safe.
#: Additions need a justification a reviewer can check.
_THREAD_SHARED_ALLOWLIST = {
    # speculation's per-attempt context is a contextvar; only the
    # blocklist is shared — and it is lock-guarded after this PR.
}

#: container-mutating method names on dict/list/set/deque
_MUTATING_METHODS = {"append", "extend", "add", "update", "pop",
                     "popitem", "remove", "discard", "clear",
                     "setdefault", "insert", "appendleft", "popleft",
                     "move_to_end"}

_MUTABLE_CTORS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                  "deque", "Counter", "WeakKeyDictionary",
                  "WeakValueDictionary"}


def _is_mutable_container(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set,
                         ast.ListComp, ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        return chain.split(".")[-1] in _MUTABLE_CTORS
    return False


def _is_lock_guard(with_node: ast.With) -> bool:
    for item in with_node.items:
        chain = _attr_chain(item.context_expr).lower()
        if isinstance(item.context_expr, ast.Call):
            chain = _attr_chain(item.context_expr.func).lower()
        if "lock" in chain or "cond" in chain:
            return True
    return False


def _check_thread_shared(rel: str, tree: ast.AST,
                         diags: List[Diagnostic]):
    if not rel.startswith(_THREAD_SHARED_DIRS):
        return
    shared_globals: dict = {}
    class_names = set()
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            class_names.add(node.name)
        target = value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        if target is not None and _is_mutable_container(value):
            shared_globals[target] = node.lineno

    def _flag(node, what, name):
        """``name`` is the allowlist key: the container's global name,
        or the attribute name for class-level singleton slots."""
        if f"{rel}:{name}" in _THREAD_SHARED_ALLOWLIST:
            return
        diags.append(make(
            "RL-THREAD-SHARED", f"{rel}:{node.lineno}",
            f"{what} written outside a lock guard in a module shared "
            "by concurrent query workers; hold a lock (with "
            "<..lock..>:), use threading.local, or allowlist "
            f"{rel}:{name} with a justification"))

    def _root_name(node: ast.AST):
        while isinstance(node, ast.Subscript):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def _is_class_attr_target(node: ast.AST):
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and (node.value.id == "cls"
                     or node.value.id in class_names))

    def walk(node, in_func: bool, guarded: bool, fn_globals):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_func = True
            fn_globals = {n for g in ast.walk(node)
                          if isinstance(g, ast.Global) for n in g.names}
        elif isinstance(node, ast.With) and _is_lock_guard(node):
            guarded = True
        if in_func and not guarded:
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        root = _root_name(t)
                        if root in shared_globals:
                            _flag(node, f"{root}[...]", root)
                    elif isinstance(t, ast.Name) and t.id in fn_globals \
                            and t.id in shared_globals:
                        _flag(node, t.id, t.id)
                    elif _is_class_attr_target(t):
                        _flag(node, f"{_attr_chain(t)} (class attribute)",
                              t.attr)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATING_METHODS:
                root = _root_name(node.func.value)
                if root in shared_globals:
                    _flag(node, f"{root}.{node.func.attr}(...)", root)
        for child in ast.iter_child_nodes(node):
            walk(child, in_func, guarded, fn_globals)

    walk(tree, False, False, set())


#: io/ modules exempt from RL-WRITE-COMMIT: the committer IS the
#: sanctioned writer, and the file cache's files are not table output
_WRITE_COMMIT_EXEMPT = ("spark_rapids_tpu/io/committer.py",
                        "spark_rapids_tpu/io/filecache.py")

#: the sanctioned callback name: write_partitioned hands these a
#: committer staging path, never a final destination
_WRITE_ONE = "_write_one"


def _open_mode_writes(node: ast.Call) -> bool:
    """Is this an ``open()`` call with a write/append/exclusive mode?
    A non-literal mode is treated as writing (it would dodge the
    audit)."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default 'r'
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return any(c in mode.value for c in "wxa")
    return True


def _check_write_commit(rel: str, tree: ast.AST,
                        diags: List[Diagnostic]):
    if not rel.startswith("spark_rapids_tpu/io/") \
            or rel in _WRITE_COMMIT_EXEMPT:
        return

    def walk(node, in_write_one: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_write_one = in_write_one or node.name == _WRITE_ONE
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in ("os.replace", "os.rename") \
                    or chain.endswith((".replace", ".rename")) \
                    and chain.startswith("os."):
                diags.append(make(
                    "RL-WRITE-COMMIT", f"{rel}:{node.lineno}",
                    f"{chain}() in an io/ writer module — promotion "
                    "into final destinations is the committer's job "
                    "(io/committer.py WriteJob.commit_task)"))
            elif not in_write_one and (
                    chain.endswith((".write_table", ".write_csv"))
                    or (chain == "open" and _open_mode_writes(node))):
                diags.append(make(
                    "RL-WRITE-COMMIT", f"{rel}:{node.lineno}",
                    f"{chain}() creates an output file outside a "
                    f"{_WRITE_ONE} staged-path callback — table "
                    "output must stage through the transactional "
                    "committer, never open a final destination"))
        for child in ast.iter_child_nodes(node):
            walk(child, in_write_one)

    walk(tree, False)


def _host_sync_call(chain: str) -> bool:
    """THE host-synchronization call set shared by the device-residency
    rules (RL-MESH-HOST and RL-KERNEL-HOST walk different scopes but
    must agree on what a host sync IS — a spelling added to one and not
    the other would silently diverge)."""
    return ((chain.endswith("device_get") and chain.startswith(
                ("jax.", "jax")))
            or chain == "host_fetch" or chain.endswith(".host_fetch")
            or chain.endswith(".block_until_ready"))


#: sanctioned mesh->host materialization points: "<rel>:<function>" ->
#: justification. The hook for new gather points — add an entry HERE
#: with a reason, never a bare suppression.
_MESH_HOST_ALLOWLIST = {
    "spark_rapids_tpu/parallel/mesh.py:mesh_gather":
        "THE sanctioned mesh->host gather point (routes through "
        "dispatch.host_fetch and counts meshGatherRows; the ICI "
        "exchange's per-shard live-count fetch comes through here)",
    "spark_rapids_tpu/parallel/mesh.py:MeshRuntime.configure":
        "np.array over a list of jax DEVICE HANDLES (building the Mesh "
        "topology array) — no device data is materialized",
    "spark_rapids_tpu/parallel/mesh.py:MeshRuntime.exchange_mesh":
        "np.array over jax device handles (submesh construction) — no "
        "device data is materialized",
}


def _check_mesh_host(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    """RL-MESH-HOST: inside parallel/ and the shard-dispatch placement
    layer, host materialization of device data (np.asarray on arrays,
    jax.device_get, dispatch.host_fetch, .block_until_ready(),
    .addressable_shards reads) is forbidden outside the sanctioned
    gather points — the static guard for 'zero host round-trips
    between exchanges': shards land once at the scan and stay
    device-resident until a sanctioned gather."""
    if not (rel.startswith("spark_rapids_tpu/parallel/")
            or rel == "spark_rapids_tpu/runtime/placement.py"):
        return

    def flag(node, what: str, func: Optional[str]):
        if f"{rel}:{func}" in _MESH_HOST_ALLOWLIST:
            return
        diags.append(make(
            "RL-MESH-HOST", f"{rel}:{node.lineno}",
            f"{what} in mesh/shard-dispatch code"
            + (f" (function {func!r})" if func else " (module level)")
            + " — device shards must stay resident between exchanges; "
            "gather through parallel.mesh.mesh_gather or allowlist the "
            "function in _MESH_HOST_ALLOWLIST with a justification"))

    def walk(node, func: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # QUALIFIED name (Class.method / outer.inner): a bare-name
            # key would exempt EVERY function sharing the allowlisted
            # name anywhere in the file
            func = f"{func}.{node.name}" if func else node.name
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in ("np.asarray", "numpy.asarray", "asarray",
                         "np.array", "numpy.array"):
                # bare 'asarray' covers `from numpy import asarray`;
                # np.array() forces the same device->host copy
                flag(node, f"{chain}()", func)
            elif _host_sync_call(chain):
                flag(node, f"{chain}()", func)
        elif isinstance(node, ast.Attribute) \
                and node.attr == "addressable_shards":
            flag(node, ".addressable_shards read", func)
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, None)


#: sanctioned host-side operations inside kernels/:
#: "<rel>:<qualified function>" -> justification. The hook for new
#: exceptions — add an entry HERE with a reason, never a bare
#: suppression.
_KERNEL_HOST_ALLOWLIST = {}


def _check_kernel_host(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    """RL-KERNEL-HOST: kernels/ modules run inside other traces — no
    numpy at all (materialization happens the moment an np.* call sees
    a device array) and no host syncs. The static guard for 'a Pallas
    primitive never stalls the program that embeds it'."""
    if not rel.startswith("spark_rapids_tpu/kernels/"):
        return

    def flag(node, what: str, func: Optional[str]):
        if f"{rel}:{func}" in _KERNEL_HOST_ALLOWLIST:
            return
        diags.append(make(
            "RL-KERNEL-HOST", f"{rel}:{node.lineno}",
            f"{what} in the Pallas kernel layer"
            + (f" (function {func!r})" if func else " (module level)")
            + " — kernels/ is pure device code traced into other "
            "programs; keep host work at the dispatch sites or "
            "allowlist the function in _KERNEL_HOST_ALLOWLIST with a "
            "justification"))

    def walk(node, func: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            func = f"{func}.{node.name}" if func else node.name
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = getattr(node, "module", None)
            names = [a.name for a in node.names]
            if mod == "numpy" or "numpy" in names \
                    or any(n.startswith("numpy.") for n in names) \
                    or (mod or "").startswith("numpy."):
                flag(node, "numpy import", func)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain.startswith(("np.", "numpy.")):
                flag(node, f"{chain}()", func)
            elif _host_sync_call(chain):
                flag(node, f"{chain}()", func)
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, None)


#: sanctioned raw device_put sites inside execs//ops/:
#: "<rel>:<qualified function>" -> justification. The hook for new
#: exceptions — add an entry HERE with a reason, never a bare
#: suppression. Table-sized landings are NEVER eligible: they belong
#: on the arbiter-accounted DeviceTable.from_host path.
_MEM_ACCOUNT_ALLOWLIST = {
    "spark_rapids_tpu/execs/mesh.py:TpuMeshRelandExec._reland":
        "re-lands a 4-element uint32 DIGEST scalar (gather-integrity "
        "checksum, ~16 bytes) onto device 0 — validation overhead, "
        "not a table landing; budget accounting at this size would be "
        "pure ledger noise",
}


def _check_mem_account(rel: str, tree: ast.AST,
                       diags: List[Diagnostic]):
    """RL-MEM-ACCOUNT: device landings in execs//ops/ must route
    through arbiter-accounted paths — a raw jax.device_put there lands
    bytes the MemoryArbiter never sees, and the hard budget contract
    (zero violations under scale_test --device-budget) silently
    breaks."""
    if not rel.startswith(("spark_rapids_tpu/execs/",
                           "spark_rapids_tpu/ops/")):
        return

    def flag(node, what: str, func):
        if f"{rel}:{func}" in _MEM_ACCOUNT_ALLOWLIST:
            return
        diags.append(make(
            "RL-MEM-ACCOUNT", f"{rel}:{node.lineno}",
            f"{what} in a device-landing layer"
            + (f" (function {func!r})" if func else " (module level)")
            + " — land through DeviceTable.from_host so the memory "
            "arbiter accounts the bytes, or allowlist the function in "
            "_MEM_ACCOUNT_ALLOWLIST with a justification"))

    def walk(node, func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            func = f"{func}.{node.name}" if func else node.name
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            # `from jax import device_put` would make the call below
            # invisible to the chain matcher — ban the import form too
            for a in node.names:
                if a.name == "device_put":
                    flag(node, "importing jax.device_put", func)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain == "jax.device_put" \
                    or chain.endswith(".device_put") \
                    or chain == "device_put":
                flag(node, f"{chain}()", func)
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, None)


#: the module RL-OBS-PASSIVE governs (the telemetry sampler + flight
#: recorder — both run off the query path by contract)
_OBS_PASSIVE_MODULE = "spark_rapids_tpu/obs/telemetry.py"

#: sanctioned exceptions: "<rel>:<qualified function>" -> justification
_OBS_PASSIVE_ALLOWLIST: dict = {}

#: lock-name fragments that mark a QUERY-PATH lock (the device
#: semaphore, the scheduler's condition, the session's obs lock) —
#: the sampler's own ring lock and the snapshot surfaces' internal
#: locks are fine (each bounds its hold to a dict copy)
_OBS_PASSIVE_LOCK_TOKENS = ("semaphore", "_cond", "_obs_lock")

#: call names that DRIVE execution — the passive module may read
#: state, never create it
_OBS_PASSIVE_EXEC_CALLS = {"execute", "execute_cpu", "execute_masked",
                           "collect", "collect_table", "collect_cpu"}


def _check_obs_passive(rel: str, tree: ast.AST,
                       diags: List[Diagnostic]):
    """RL-OBS-PASSIVE: the telemetry sampler thread may not call
    host_fetch/device syncs, touch jax at all, drive query execution,
    or take query-path locks — sampling must never perturb the
    execution it observes."""
    if rel != _OBS_PASSIVE_MODULE:
        return

    def flag(node, what: str, func: Optional[str]):
        if f"{rel}:{func}" in _OBS_PASSIVE_ALLOWLIST:
            return
        diags.append(make(
            "RL-OBS-PASSIVE", f"{rel}:{node.lineno}",
            f"{what} in the passive telemetry module"
            + (f" (function {func!r})" if func else " (module level)")
            + " — the sampler must never perturb execution: read the "
            "bounded snapshot surfaces only, or allowlist the function "
            "in _OBS_PASSIVE_ALLOWLIST with a justification"))

    def _names_query_lock(expr: ast.AST) -> Optional[str]:
        chain = _attr_chain(expr)
        if isinstance(expr, ast.Call):
            chain = _attr_chain(expr.func)
        low = chain.lower()
        for tok in _OBS_PASSIVE_LOCK_TOKENS:
            if tok in low:
                return chain
        return None

    def walk(node, func: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            func = f"{func}.{node.name}" if func else node.name
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = getattr(node, "module", None) or ""
            names = [a.name for a in node.names]
            if mod == "jax" or mod.startswith("jax.") \
                    or any(n == "jax" or n.startswith("jax.")
                           for n in names):
                flag(node, "jax import (device work)", func)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain.startswith(("jax.", "jnp.")):
                flag(node, f"{chain}() (device work)", func)
            elif _host_sync_call(chain):
                flag(node, f"{chain}() (host sync)", func)
            elif chain.split(".")[-1] == "finalize_observation":
                flag(node, f"{chain}() (forces the deferred device "
                           "row-count fetch)", func)
            elif chain.split(".")[-1] in _OBS_PASSIVE_EXEC_CALLS:
                flag(node, f"{chain}() (drives query execution)", func)
            elif chain.split(".")[-1] == "acquire":
                locked = _names_query_lock(node.func.value) \
                    if isinstance(node.func, ast.Attribute) else None
                if locked:
                    flag(node, f"{chain}() (query-path lock)", func)
        elif isinstance(node, ast.With):
            for item in node.items:
                locked = _names_query_lock(item.context_expr)
                if locked:
                    flag(node, f"with {locked} (query-path lock)", func)
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, None)


def _check_dead_lambdas(rel: str, tree: ast.AST,
                        diags: List[Diagnostic]):
    lambda_defs = {}
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Lambda):
            name = node.targets[0].id
            lambda_defs.setdefault(name, node.lineno)
        elif isinstance(node, ast.Name) and \
                isinstance(node.ctx, ast.Load):
            used.add(node.id)
    for name, lineno in sorted(lambda_defs.items(), key=lambda kv: kv[1]):
        if name not in used:
            diags.append(make(
                "RL-DEAD-LAMBDA", f"{rel}:{lineno}",
                f"lambda bound to {name!r} is never used — dead code"))


#: the ONLY names streaming/ may import from service/result_cache — the
#: invalidation-epoch API (all re-exported from plan/fingerprint).
#: Anything else (ResultCache itself, its mutators) is a second write
#: path into cache coherence.
_MV_EPOCH_ALLOWED_IMPORTS = frozenset({
    "GLOBAL_EPOCH_KEY",
    "bump_invalidation_epoch",
    "bump_table_epoch",
    "delta_table_id",
    "epoch_snapshot",
    "epochs_current",
    "invalidation_epoch",
    "plan_table_ids",
    "register_epoch_listener",
    "table_epoch",
    "unregister_epoch_listener",
})

_MV_CACHE_MUTATORS = ("put", "clear", "pop", "evict", "invalidate")


def _check_mv_epoch(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    """RL-MV-EPOCH: MV/stream maintenance lives in streaming/ and must
    drive cache coherence through the invalidation-epoch API only —
    a direct result-cache mutation there would race the scheduler's
    epoch-vector staleness checks."""
    if not rel.startswith("spark_rapids_tpu/streaming/"):
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("service.result_cache"):
            for alias in node.names:
                if alias.name not in _MV_EPOCH_ALLOWED_IMPORTS:
                    diags.append(make(
                        "RL-MV-EPOCH", f"{rel}:{node.lineno}",
                        f"import of {alias.name!r} from service/"
                        "result_cache in streaming/ — only the "
                        "invalidation-epoch API may cross this "
                        "boundary"))
        elif isinstance(node, ast.Attribute) and node.attr == "_entries":
            diags.append(make(
                "RL-MV-EPOCH", f"{rel}:{node.lineno}",
                "direct access to a result cache's _entries from "
                "streaming/ — mark staleness via bump_table_epoch, "
                "never by reaching into the cache"))
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            parts = chain.split(".")
            if len(parts) >= 2 and parts[-1] in _MV_CACHE_MUTATORS \
                    and any("result_cache" in p or p == "cache"
                            for p in parts[:-1]):
                diags.append(make(
                    "RL-MV-EPOCH", f"{rel}:{node.lineno}",
                    f"{chain}() mutates a result cache from "
                    "streaming/ — MV maintenance owns its own "
                    "tables; cache invalidation goes through the "
                    "epoch API"))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def lint_repo(repo_root: Optional[str] = None) -> List[Diagnostic]:
    root = _repo_root(repo_root)
    from spark_rapids_tpu.lint.registry_audit import _import_full_package
    _import_full_package()
    from spark_rapids_tpu import conf as C
    declared = set(C.registry())
    diags: List[Diagnostic] = []
    fault_calls: dict = {}
    for path in _iter_source_files(root):
        rel = _rel(root, path)
        if rel.startswith("spark_rapids_tpu/lint/"):
            continue  # the lint's own rule tables name forbidden patterns
        with open(path) as f:
            src = f.read()
        tree = ast.parse(src, filename=rel)  # unparseable repo = hard error
        _check_host_sync(rel, tree, diags)
        _check_jnp_scope(rel, tree, diags)
        _check_conf_keys(rel, tree, declared, diags)
        _check_nondeterminism(rel, tree, diags)
        _check_dead_lambdas(rel, tree, diags)
        _check_thread_shared(rel, tree, diags)
        _check_write_commit(rel, tree, diags)
        _check_mesh_host(rel, tree, diags)
        _check_kernel_host(rel, tree, diags)
        _check_obs_passive(rel, tree, diags)
        _check_mem_account(rel, tree, diags)
        _check_mv_epoch(rel, tree, diags)
        _check_fault_sites(rel, tree, fault_calls, diags)
    _check_fault_registry(fault_calls, diags)
    return diags
