// Native string dictionary codec for spark_rapids_tpu.
//
// Reference analog: the reference's hot string paths live in C++/CUDA
// (cuDF strings columns + JNI); here the host-side ORDER-PRESERVING
// dictionary encode (columnar/column.py _encode_strings) is the Python
// bottleneck. The Python side converts the object array to numpy's
// fixed-width UTF-32 representation in C (astype('U')); this codec sorts
// row indices by code-point order (== UTF-8 byte order == Spark's
// UTF8String.compareTo order), dedupes, and assigns dictionary codes.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC strcodec.cpp -o libstrcodec.so
// (driven lazily by spark_rapids_tpu/native.py; pure-numpy fallback stays.)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// `chars` is an (n x width) row-major array of UTF-32 code points with
// NUL padding (numpy 'U' layout). Outputs: codes[i] = dictionary code of
// row i; dict_row[k] = a row index holding dictionary entry k. Returns
// the dictionary size, or -1 on error.
int64_t encode_sorted_dict_u32(const uint32_t* chars,
                               int64_t n,
                               int64_t width,
                               int32_t* codes,
                               int64_t* dict_row) {
    if (n <= 0) return 0;
    std::vector<int32_t> order(static_cast<size_t>(n));
    std::iota(order.begin(), order.end(), 0);

    const uint32_t* base = chars;
    auto cmp = [base, width](int32_t a, int32_t b) {
        const uint32_t* pa = base + static_cast<int64_t>(a) * width;
        const uint32_t* pb = base + static_cast<int64_t>(b) * width;
        for (int64_t k = 0; k < width; ++k) {
            if (pa[k] != pb[k]) return pa[k] < pb[k];
        }
        return false;
    };
    std::sort(order.begin(), order.end(), cmp);

    auto eq = [base, width](int32_t a, int32_t b) {
        return std::memcmp(base + static_cast<int64_t>(a) * width,
                           base + static_cast<int64_t>(b) * width,
                           static_cast<size_t>(width) * 4) == 0;
    };

    int64_t ndict = 0;
    int32_t prev_row = -1;
    for (int64_t j = 0; j < n; ++j) {
        const int32_t row = order[j];
        if (prev_row < 0 || !eq(row, prev_row)) {
            dict_row[ndict++] = row;
            prev_row = row;
        }
        codes[row] = static_cast<int32_t>(ndict - 1);
    }
    return ndict;
}

}  // extern "C"
