"""Timezone transition tables on device.

Reference (SURVEY.md §2.9): ``GpuTimeZoneDB`` (spark-rapids-jni) loads the
Java timezone database's transition rules into device memory so
from/to_utc_timestamp and tz-aware casts evaluate on the GPU for DST
zones, not just fixed offsets (fixed offsets were the reference's original
carve-out, later widened — mirrored here).

TPU mapping: transitions are derived from the system zoneinfo database by
scanning 1900..2100 at day granularity and bisecting each offset change
to the exact second (zoneinfo does not expose raw transitions). Per zone,
two device-resident tables:

- UTC direction: (transition instant in UTC micros, offset micros) —
  ``from_utc`` looks up by UTC instant.
- WALL direction: (transition instant in local-wall micros, offset
  micros) — ``to_utc`` looks up by wall clock, resolving DST overlaps to
  the EARLIER offset and gaps to the post-transition offset (java.time
  ``ZonedDateTime.ofLocal`` semantics, which Spark uses).

Lookups are ``searchsorted`` over the tables — one gather on device."""

from __future__ import annotations

import datetime as _dt
import threading
from typing import Dict, Optional, Tuple

import numpy as np

_EPOCH = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
#: table coverage window. Instants outside it use the boundary offset —
#: a documented carve-out (the reference's GpuTimeZoneDB likewise builds
#: transitions to a max year). 1850..2200 covers Spark's practical range;
#: sub-day double transitions (not observed in tzdata) would be missed
#: by the day-granularity scan.
_SCAN_START = _dt.datetime(1850, 1, 1, tzinfo=_dt.timezone.utc)
_SCAN_END = _dt.datetime(2200, 1, 1, tzinfo=_dt.timezone.utc)
_US = _dt.timedelta(microseconds=1)


def _offset_micros_at(zone, utc_dt: _dt.datetime) -> int:
    off = utc_dt.astimezone(zone).utcoffset()
    return int(off / _US)


def _find_transitions(zone) -> Tuple[np.ndarray, np.ndarray]:
    """(utc transition instants in micros, offset micros AFTER each
    instant). Index 0 is a sentinel (-inf, initial offset)."""
    day = _dt.timedelta(days=1)
    instants = [-(1 << 62)]
    offsets = [_offset_micros_at(zone, _SCAN_START)]
    t = _SCAN_START
    prev_off = offsets[0]
    while t < _SCAN_END:
        nxt = t + day
        off = _offset_micros_at(zone, nxt)
        if off != prev_off:
            # bisect the change point to the second
            lo, hi = t, nxt
            while hi - lo > _dt.timedelta(seconds=1):
                mid = lo + (hi - lo) / 2
                mid = mid.replace(microsecond=0)
                if mid <= lo:
                    break
                if _offset_micros_at(zone, mid) == prev_off:
                    lo = mid
                else:
                    hi = mid
            instants.append(int((hi - _EPOCH) / _US))
            offsets.append(off)
            prev_off = off
        t = nxt
    return (np.asarray(instants, dtype=np.int64),
            np.asarray(offsets, dtype=np.int64))


class TimeZoneDB:
    """Process-wide cache of per-zone transition tables (GpuTimeZoneDB
    analog). ``tables(name)`` returns numpy; ``device_tables(name)``
    returns jnp arrays cached for reuse inside jitted kernels."""

    _lock = threading.Lock()
    _cache: Dict[str, Tuple[np.ndarray, np.ndarray,
                            np.ndarray, np.ndarray]] = {}

    @classmethod
    def supported(cls, name: str) -> bool:
        try:
            cls.tables(name)
            return True
        except Exception:
            return False

    @classmethod
    def tables(cls, name: str):
        """(utc_instants, utc_offsets, wall_instants, wall_offsets)."""
        with cls._lock:
            hit = cls._cache.get(name)
        if hit is not None:
            return hit
        from zoneinfo import ZoneInfo
        zone = ZoneInfo(name)
        utc_instants, offsets = _find_transitions(zone)
        # wall-clock transition table for the to-UTC direction: each
        # transition happens at wall time (instant + NEW offset) for the
        # gap bound and (instant + OLD offset) for the overlap bound.
        # Using instant + max(old, new) as the boundary with the EARLIER
        # (pre-transition) offset below it implements java.time ofLocal:
        #  - overlap (offset decreases): wall times in the repeated hour
        #    are below instant+old -> earlier offset. ✓
        #  - gap (offset increases): non-existent wall times are below
        #    instant+new -> resolved with the OLD offset, mapping them
        #    forward past the gap. ✓ (ofLocal shifts by the gap length)
        wall_instants = [-(1 << 62)]
        wall_offsets = [offsets[0]]
        for i in range(1, len(utc_instants)):
            old, new = offsets[i - 1], offsets[i]
            wall_instants.append(utc_instants[i] + max(old, new))
            wall_offsets.append(new)
        out = (utc_instants, offsets,
               np.asarray(wall_instants, dtype=np.int64),
               np.asarray(wall_offsets, dtype=np.int64))
        with cls._lock:
            cls._cache[name] = out
        return out

    # NOTE: no jnp-array cache — these functions run INSIDE jit traces,
    # where jnp.asarray returns per-trace constants; caching one would
    # leak a tracer into other traces (UnexpectedTracerError). The numpy
    # tables embed as XLA constants per compiled kernel, which the compile
    # cache already de-duplicates by expression key.


def from_utc_micros_host(micros: np.ndarray, name: str) -> np.ndarray:
    ui, uo, _wi, _wo = TimeZoneDB.tables(name)
    idx = np.searchsorted(ui, micros, side="right") - 1
    return micros + uo[idx]


def to_utc_micros_host(micros: np.ndarray, name: str) -> np.ndarray:
    _ui, _uo, wi, wo = TimeZoneDB.tables(name)
    idx = np.searchsorted(wi, micros, side="right") - 1
    return micros - wo[idx]


def from_utc_micros_dev(micros, name: str):
    import jax.numpy as jnp
    ui, uo, _wi, _wo = TimeZoneDB.tables(name)
    idx = jnp.searchsorted(jnp.asarray(ui), micros, side="right") - 1
    return micros + jnp.asarray(uo)[idx]


def to_utc_micros_dev(micros, name: str):
    import jax.numpy as jnp
    _ui, _uo, wi, wo = TimeZoneDB.tables(name)
    idx = jnp.searchsorted(jnp.asarray(wi), micros, side="right") - 1
    return micros - jnp.asarray(wo)[idx]
