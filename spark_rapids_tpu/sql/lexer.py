"""SQL lexer: text -> position-annotated token stream.

Hand-written (no re-based scanner tables) so every token carries its
1-based (line, col) and error messages can point into the query text the
way Spark's ParseException does. Keywords are case-insensitive;
identifiers keep their original spelling (the plan layer is
case-sensitive, matching this engine's DataFrame API)."""

from __future__ import annotations

from typing import List, NamedTuple, Optional

from spark_rapids_tpu.sql.errors import SqlParseError

# token kinds
IDENT = "IDENT"          # bare or `quoted` identifier
NUMBER = "NUMBER"        # value holds (python value, is_decimal_suffix)
STRING = "STRING"        # single-quoted literal, unescaped
OP = "OP"                # punctuation / operator
HINT = "HINT"            # /*+ ... */ contents
EOF = "EOF"

#: IDENT token value marking a backtick/double-quoted identifier — the
#: parser never treats a quoted identifier as a keyword, so reserved
#: words stay usable as column/table names (`order`, `from`, ...)
QUOTED = "quoted-ident"

#: multi-char operators, longest first
_OPS = ["<=>", "<>", "!=", "<=", ">=", "||", "==",
        "(", ")", ",", ".", "+", "-", "*", "/", "%", "<", ">", "=", ";"]


class Token(NamedTuple):
    kind: str
    text: str            # raw text (uppercased for keyword checks by parser)
    value: object        # parsed value for NUMBER/STRING
    line: int
    col: int

    def upper(self) -> str:
        return self.text.upper()


def _is_ident_start(c: str) -> bool:
    return c.isalpha() or c == "_"


def _is_ident_part(c: str) -> bool:
    return c.isalnum() or c == "_"


def tokenize(sql: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(sql)
    line, col = 1, 1

    def err(msg: str, ln: int, cl: int) -> SqlParseError:
        return SqlParseError(msg, sql, ln, cl)

    def advance(k: int) -> None:
        nonlocal i, line, col
        for _ in range(k):
            if i < n and sql[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            advance(1)
            continue
        # comments: -- to end of line; /* ... */ (a /*+ ... */ is a HINT)
        if sql.startswith("--", i):
            while i < n and sql[i] != "\n":
                advance(1)
            continue
        if sql.startswith("/*", i):
            ln, cl = line, col
            is_hint = sql.startswith("/*+", i)
            end = sql.find("*/", i + 2)
            if end < 0:
                raise err("unterminated comment", ln, cl)
            if is_hint:
                toks.append(Token(HINT, sql[i + 3:end].strip(), None, ln, cl))
            advance(end + 2 - i)
            continue
        if c == "'":
            ln, cl = line, col
            advance(1)
            buf = []
            while True:
                if i >= n:
                    raise err("unterminated string literal", ln, cl)
                if sql[i] == "'":
                    if i + 1 < n and sql[i + 1] == "'":  # '' escape
                        buf.append("'")
                        advance(2)
                        continue
                    advance(1)
                    break
                if sql[i] == "\\" and i + 1 < n:  # backslash escapes
                    nxt = sql[i + 1]
                    buf.append({"n": "\n", "t": "\t"}.get(nxt, nxt))
                    advance(2)
                    continue
                buf.append(sql[i])
                advance(1)
            toks.append(Token(STRING, "'...'", "".join(buf), ln, cl))
            continue
        if c in "`\"":  # quoted identifier
            ln, cl = line, col
            quote = c
            advance(1)
            start = i
            while i < n and sql[i] != quote:
                advance(1)
            if i >= n:
                raise err("unterminated quoted identifier", ln, cl)
            toks.append(Token(IDENT, sql[start:i], QUOTED, ln, cl))
            advance(1)
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            ln, cl = line, col
            start = i
            seen_dot = seen_exp = False
            while i < n:
                ch = sql[i]
                if ch.isdigit():
                    advance(1)
                elif ch == "." and not seen_dot and not seen_exp:
                    # `1.foo` is member access on a number? no — numbers
                    # never precede idents here; a dot followed by a digit
                    # continues the number
                    seen_dot = True
                    advance(1)
                elif ch in "eE" and not seen_exp and i + 1 < n and (
                        sql[i + 1].isdigit()
                        or (sql[i + 1] in "+-" and i + 2 < n
                            and sql[i + 2].isdigit())):
                    seen_exp = True
                    advance(2 if sql[i + 1] in "+-" else 1)
                else:
                    break
            text = sql[start:i]
            # Spark literal suffixes: L/l bigint, D/d double, BD decimal
            suffix = ""
            if i + 1 < n and sql[i:i + 2].upper() == "BD":
                suffix = "BD"
                advance(2)
            elif i < n and sql[i].upper() in ("L", "D") \
                    and not (i + 1 < n and _is_ident_part(sql[i + 1])):
                suffix = sql[i].upper()
                advance(1)
            if suffix == "BD":
                import decimal
                value = decimal.Decimal(text)
            elif suffix == "D" or seen_dot or seen_exp:
                value = float(text)
            else:
                value = int(text)
            toks.append(Token(NUMBER, text, value, ln, cl))
            continue
        if _is_ident_start(c):
            ln, cl = line, col
            start = i
            while i < n and _is_ident_part(sql[i]):
                advance(1)
            toks.append(Token(IDENT, sql[start:i], None, ln, cl))
            continue
        matched: Optional[str] = None
        for op in _OPS:
            if sql.startswith(op, i):
                matched = op
                break
        if matched is None:
            raise err(f"unexpected character {c!r}", line, col)
        toks.append(Token(OP, matched, None, line, col))
        advance(len(matched))
    toks.append(Token(EOF, "<eof>", None, line, col))
    return toks
