"""Spillable shuffle buffer catalogs.

Reference (SURVEY.md §2.6): ``ShuffleBufferCatalog.scala`` — in UCX mode the
caching writer (``RapidsCachingWriter``, RapidsShuffleInternalManagerBase
.scala:1078) keeps shuffle output resident as spillable buffers served
directly to peers instead of writing Spark shuffle files;
``ShuffleReceivedBufferCatalog.scala`` registers fetched blocks on the read
side. Both integrate with the spill framework so cached shuffle data
demotes under memory pressure.

TPU mapping: shuffle blobs are packed host bytes (serializer.pack_table
output, already compressed by the resolved codec). The catalog bounds the
host-resident total and demotes least-recently-touched blobs to disk files;
serving or reading a spilled blob faults it back transparently. Accounting
(host bytes, spill counts) feeds the same metrics the buffer catalog
reports for execution spills."""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from spark_rapids_tpu.errors import ColumnarProcessingError

BlockId = Tuple[int, int, int]  # (shuffle_id, map_id, partition_id)


class _CachedBlob:
    __slots__ = ("block_id", "data", "disk_path", "length", "last_touch",
                 "lock")

    def __init__(self, block_id: BlockId, data: bytes):
        self.block_id = block_id
        self.data: Optional[bytes] = data
        self.disk_path: Optional[str] = None
        self.length = len(data)
        self.last_touch = time.monotonic()
        self.lock = threading.Lock()


class ShuffleBufferCatalog:
    """Write-side catalog of cached shuffle blocks for one executor."""

    def __init__(self, host_limit_bytes: int = 1 << 30,
                 disk_dir: Optional[str] = None):
        self.host_limit_bytes = host_limit_bytes
        self.disk_dir = disk_dir or tempfile.mkdtemp(
            prefix="rapids_tpu_shufcache_")
        self._lock = threading.RLock()
        self._blobs: Dict[BlockId, _CachedBlob] = {}
        self._host_bytes = 0
        self.spill_count = 0
        self.spilled_bytes = 0

    # -- write side ---------------------------------------------------------
    def add_block(self, block_id: BlockId, data: bytes):
        with self._lock:
            if block_id in self._blobs:
                raise ColumnarProcessingError(
                    f"duplicate shuffle block {block_id}")
            self._blobs[block_id] = _CachedBlob(block_id, data)
            self._host_bytes += len(data)
        self._enforce_limit()

    def block_length(self, block_id: BlockId) -> Optional[int]:
        with self._lock:
            blob = self._blobs.get(block_id)
            return None if blob is None else blob.length

    def blocks_for_partition(self, shuffle_id: int, partition_id: int,
                             map_ids: Optional[List[int]] = None
                             ) -> List[Tuple[BlockId, int]]:
        """(block_id, length) for every cached block of a reduce partition,
        in map order — the metadata-response payload."""
        with self._lock:
            out = []
            for bid, blob in self._blobs.items():
                sid, mid, pid = bid
                if sid == shuffle_id and pid == partition_id and (
                        map_ids is None or mid in map_ids):
                    out.append((bid, blob.length))
            out.sort(key=lambda x: x[0][1])
            return out

    # -- serve side ---------------------------------------------------------
    def get_block(self, block_id: BlockId) -> bytes:
        """Blob bytes, faulting back from disk when spilled."""
        with self._lock:
            blob = self._blobs.get(block_id)
        if blob is None:
            raise ColumnarProcessingError(
                f"unknown shuffle block {block_id}")
        with blob.lock:
            blob.last_touch = time.monotonic()
            if blob.data is not None:
                return blob.data
            assert blob.disk_path is not None
            with open(blob.disk_path, "rb") as f:
                data = f.read()
            if len(data) != blob.length:
                raise ColumnarProcessingError(
                    f"shuffle block {block_id} truncated on disk")
            # serve from disk without re-admitting to the host tier (a hot
            # re-read pattern would thrash; the reference keeps spilled
            # buffers in their tier until explicitly unspilled)
            return data

    # -- spill --------------------------------------------------------------
    def _enforce_limit(self):
        with self._lock:
            if self._host_bytes <= self.host_limit_bytes:
                return
            order = sorted(self._blobs.values(), key=lambda b: b.last_touch)
        for blob in order:
            with blob.lock:
                if blob.data is None:
                    continue
                fd, path = tempfile.mkstemp(
                    prefix=f"shufblk_{blob.block_id[0]}_", suffix=".bin",
                    dir=self.disk_dir)
                with os.fdopen(fd, "wb") as f:
                    f.write(blob.data)
                blob.disk_path = path
                freed = len(blob.data)
                blob.data = None
            with self._lock:
                self._host_bytes -= freed
                self.spill_count += 1
                self.spilled_bytes += freed
                if self._host_bytes <= self.host_limit_bytes:
                    return

    @property
    def host_bytes(self) -> int:
        with self._lock:
            return self._host_bytes

    def remove_block(self, block_id: BlockId):
        """Withdraw one block (failed-attempt cleanup — P2PWriteHandle)."""
        with self._lock:
            blob = self._blobs.pop(block_id, None)
        if blob is None:
            return
        # blob.lock orders against a concurrent _enforce_limit spill of
        # this blob (it flips data->disk and decrements _host_bytes)
        with blob.lock:
            if blob.data is not None:
                with self._lock:
                    self._host_bytes -= len(blob.data)
                blob.data = None
            if blob.disk_path and os.path.exists(blob.disk_path):
                os.unlink(blob.disk_path)

    # -- lifecycle ----------------------------------------------------------
    def remove_shuffle(self, shuffle_id: int):
        with self._lock:
            doomed = [self._blobs.pop(bid) for bid in list(self._blobs)
                      if bid[0] == shuffle_id]
        for blob in doomed:
            with blob.lock:
                if blob.data is not None:
                    with self._lock:
                        self._host_bytes -= len(blob.data)
                    blob.data = None
                if blob.disk_path and os.path.exists(blob.disk_path):
                    os.unlink(blob.disk_path)


class ShuffleReceivedBufferCatalog:
    """Read-side registry of fetched blocks awaiting deserialization
    (ShuffleReceivedBufferCatalog analog). Bounded only by the consumer:
    the client hands blobs over as they complete and the reader iterator
    drains them in arrival order."""

    def __init__(self):
        self._lock = threading.Condition()
        self._queue: List[Tuple[BlockId, bytes]] = []
        self._expected: Optional[int] = None
        self._received = 0
        self._error: Optional[str] = None

    def expect(self, n: int):
        with self._lock:
            self._expected = n
            self._lock.notify_all()

    def add(self, block_id: BlockId, data: bytes):
        with self._lock:
            self._queue.append((block_id, data))
            self._received += 1
            self._lock.notify_all()

    def fail(self, message: str):
        with self._lock:
            self._error = message
            self._lock.notify_all()

    def drain(self, timeout: float = 300.0) -> Iterator[Tuple[BlockId, bytes]]:
        """Yield blocks as they arrive until all expected ones came in."""
        deadline = time.monotonic() + timeout
        yielded = 0
        while True:
            with self._lock:
                while (not self._queue and self._error is None
                       and (self._expected is None
                            or yielded + len(self._queue) < self._expected)):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._lock.wait(
                            timeout=min(remaining, 5.0)):
                        if time.monotonic() >= deadline:
                            raise ColumnarProcessingError(
                                "timed out waiting for shuffle blocks")
                if self._error is not None:
                    raise ColumnarProcessingError(
                        f"shuffle fetch failed: {self._error}")
                if self._queue:
                    item = self._queue.pop(0)
                else:
                    return  # all expected blocks yielded
            yielded += 1
            yield item
            if self._expected is not None and yielded >= self._expected:
                return
