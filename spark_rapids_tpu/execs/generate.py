"""TPU Generate exec (explode / posexplode [outer]).

Reference: GpuGenerateExec.scala (~1,600 LoC) — SURVEY.md §2.3 / VERDICT r1
item 6. TPU-first shape: the array column already lives flattened as
(offsets, elements, element-validity), so "explode" is a GATHER, not a
loop — each element slot finds its source row with one searchsorted over
the offsets, the other columns gather by that row id, and one compaction
scatter drops dead slots. Outer mode appends one null row per null/empty
array with the same unmatched-row trick the joins use. All static shapes:
output capacity = element capacity (+ row capacity when outer)."""

from __future__ import annotations

from typing import List, Sequence

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable, bucket_for
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops.expr import (
    DevVal,
    Expression,
    NodePrep,
    PrepCtx,
    EvalCtx,
    _prep_trace_key,
    _walk_eval,
    _walk_prep,
    shared_traces,
)


class TpuGenerateExec(TpuExec):
    def __init__(self, child: TpuExec, gen_child: Expression,
                 pos: bool, outer: bool, out_names: Sequence[str],
                 required: Sequence[str] = ()):
        super().__init__()
        self.children = (child,)
        self.gen_child = gen_child
        self.pos = pos
        self.outer = outer
        self.out_names = list(out_names)
        self.required = list(required)

    def output_schema(self):
        child_schema = dict(self.children[0].output_schema())
        out = [(n, child_schema[n]) for n in self.required]
        i = 0
        if self.pos:
            out.append((self.out_names[i], T.INT))
            i += 1
        out.append((self.out_names[i],
                    self.gen_child.data_type.element_type))
        return out

    def describe(self):
        kind = ("posexplode" if self.pos else "explode") + \
            ("_outer" if self.outer else "")
        return f"TpuGenerate[{kind}]"

    def execute(self):
        from spark_rapids_tpu.runtime.retry import with_retry
        for batch in self.children[0].execute():
            yield from with_retry(batch, self._generate, splittable=False)

    def _generate(self, full: DeviceTable) -> DeviceTable:
        # evaluate the generator over the FULL child table, pass through
        # only the required (pruned) columns
        keep = [full.names.index(n) for n in self.required]
        table = DeviceTable([full.names[i] for i in keep],
                            [full.columns[i] for i in keep],
                            full.nrows_dev, full.capacity)
        pctx = PrepCtx(full)
        preps: List[NodePrep] = []
        _walk_prep(self.gen_child, pctx, preps)
        gen_cols = tuple(DevVal(c.data, c.validity) for c in full.columns)
        cols = tuple(DevVal(c.data, c.validity) for c in table.columns)
        from spark_rapids_tpu.dispatch import prep_aux
        aux = prep_aux(pctx)
        cap = table.capacity

        # element capacity comes from the evaluated array column; for a
        # plain column ref it is the upload's bucket
        traces = shared_traces(
            ("generate", self.gen_child.key(), self.pos, self.outer,
             table.schema_key()[0]))

        # learn ecap via ABSTRACT evaluation (no device compute; the jitted
        # kernel evaluates for real inside its trace)
        gen_child = self.gen_child

        def _shape_probe(gc, a, n):
            ctx = EvalCtx(gc, a, n, cap)
            ctx._prep_iter = iter(preps)
            return _walk_eval(gen_child, ctx)

        shaped = jax.eval_shape(_shape_probe, gen_cols, aux, table.nrows_dev)
        ecap = shaped.data[1].shape[0]
        out_cap = bucket_for(ecap + (cap if self.outer else 0))

        tkey = (cap, ecap, out_cap, _prep_trace_key(preps),
                table.schema_key()[0])
        fn = traces.get(tkey)
        if fn is None:
            fn = tpu_jit(self._build_kernel(cap, ecap, out_cap, preps))
            traces[tkey] = fn
        out_arrays, nout = fn(gen_cols, cols, aux, table.nrows_dev)

        out_cols = []
        names = []
        for c, name, (d, v) in zip(table.columns, table.names, out_arrays):
            out_cols.append(DeviceColumn(c.dtype, d, v,
                                         dictionary=c.dictionary,
                                         dict_sorted=c.dict_sorted))
            names.append(name)
        i = len(table.columns)
        oni = 0
        if self.pos:
            d, v = out_arrays[i]
            out_cols.append(DeviceColumn(T.INT, d, v))
            names.append(self.out_names[oni])
            i += 1
            oni += 1
        d, v = out_arrays[i]
        out_cols.append(DeviceColumn(
            self.gen_child.data_type.element_type, d, v))
        names.append(self.out_names[oni])
        return DeviceTable(names, out_cols, nout, out_cap)

    def _build_kernel(self, cap: int, ecap: int, out_cap: int, preps):
        gen_child = self.gen_child
        pos = self.pos
        outer = self.outer

        def kernel(gen_cols, cols, aux, nrows):
            ctx = EvalCtx(gen_cols, aux, nrows, cap)
            ctx._prep_iter = iter(preps)
            arr = _walk_eval(gen_child, ctx)
            off, ed, ev = arr.data
            row_ok = arr.validity & (jnp.arange(cap, dtype=jnp.int32) < nrows)

            j = jnp.arange(ecap, dtype=jnp.int32)
            rid_raw = jnp.searchsorted(off, j, side="right").astype(jnp.int32) - 1
            rid = jnp.clip(rid_raw, 0, cap - 1)
            live = (j < off[-1]) & row_ok[rid]
            pos_val = j - off[rid]

            # compact live element slots to the front of out_cap
            cpos = jnp.cumsum(live.astype(jnp.int32)) - 1
            tgt = jnp.where(live, cpos, out_cap)
            n_elems = jnp.sum(live.astype(jnp.int32))

            from spark_rapids_tpu.ops.scatter32 import scatter_pair
            outs = []
            for data, valid in cols:
                outs.append(list(scatter_pair(out_cap, tgt, data[rid],
                                              valid[rid])))
            if pos:
                pd = jnp.zeros(out_cap, dtype=jnp.int32).at[tgt].set(
                    pos_val, mode="drop")
                pv = jnp.zeros(out_cap, dtype=jnp.bool_).at[tgt].set(
                    True, mode="drop")
                outs.append([pd, pv])
            vd, vv = scatter_pair(
                out_cap, tgt, jnp.where(ev, ed, jnp.zeros_like(ed)), ev)
            outs.append([vd, vv])
            nout = n_elems

            if outer:
                # rows with null/empty arrays emit ONE all-columns row with
                # null pos/element, appended after the element rows
                in_bounds = jnp.arange(cap, dtype=jnp.int32) < nrows
                empty = in_bounds & (~arr.validity | (off[1:] - off[:-1] == 0))
                epos = jnp.cumsum(empty.astype(jnp.int32)) - 1
                etgt = jnp.where(empty, n_elems + epos, out_cap)
                n_extra = jnp.sum(empty.astype(jnp.int32))
                for ci, (data, valid) in enumerate(cols):
                    outs[ci][0] = outs[ci][0].at[etgt].set(data, mode="drop")
                    outs[ci][1] = outs[ci][1].at[etgt].set(valid, mode="drop")
                # pos/element columns stay null on the appended rows
                nout = n_elems + n_extra

            return [tuple(o) for o in outs], nout

        return kernel
