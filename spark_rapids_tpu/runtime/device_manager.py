"""Device acquisition & memory setup (reference: GpuDeviceManager.scala —
picks the GPU, initializes the RMM pool, pinned pool, off-heap limits;
SURVEY.md §2.5).

TPU analog: discover devices/topology through JAX/PJRT, record HBM budget
from the conf fraction, and expose the live-arrays accounting XLA gives us.
XLA's allocator already pools HBM (BFC) — the engine's job is budget
tracking + spill/retry on top (runtime/catalog.py, runtime/retry.py)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax

from spark_rapids_tpu.conf import (
    CONCURRENT_TPU_TASKS,
    HBM_POOL_FRACTION,
    HBM_RESERVE_BYTES,
    RapidsConf,
)

_DEFAULT_HBM_BYTES = 16 << 30  # v5e has 16 GiB per chip


@dataclass
class DeviceInfo:
    device: object
    platform: str
    hbm_limit_bytes: int


class TpuDeviceManager:
    """Singleton-ish per-process device state."""

    _instance: Optional["TpuDeviceManager"] = None

    def __init__(self, conf: RapidsConf):
        self.conf = conf
        self.devices: List[object] = []
        self.info: Optional[DeviceInfo] = None
        self.initialized = False

    def initialize(self):
        if self.initialized:
            return
        self.devices = list(jax.devices())
        dev = self.devices[0]
        total = _DEFAULT_HBM_BYTES
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_limit" in stats:
            total = int(stats["bytes_limit"])
        frac = self.conf.get_entry(HBM_POOL_FRACTION)
        reserve = self.conf.get_entry(HBM_RESERVE_BYTES)
        limit = max(int(total * frac) - reserve, 256 << 20)
        self.info = DeviceInfo(device=dev, platform=dev.platform, hbm_limit_bytes=limit)
        from spark_rapids_tpu.conf import (
            HOST_MEMORY_LIMIT,
            HOST_SPILL_STORAGE_SIZE,
            PINNED_POOL_SIZE,
        )
        from spark_rapids_tpu.runtime.host_alloc import (
            HostMemoryArbiter,
            PinnedMemoryPool,
        )
        from spark_rapids_tpu.runtime.spill import BufferCatalog
        BufferCatalog.get().host_limit_bytes = \
            self.conf.get_entry(HOST_SPILL_STORAGE_SIZE)
        HostMemoryArbiter.reset(self.conf.get_entry(HOST_MEMORY_LIMIT))
        PinnedMemoryPool.initialize(self.conf.get_entry(PINNED_POOL_SIZE))
        TpuDeviceManager._instance = self
        self.initialized = True

    @classmethod
    def current(cls) -> Optional["TpuDeviceManager"]:
        return cls._instance

    def bytes_in_use(self) -> int:
        try:
            stats = self.info.device.memory_stats()
            return int(stats.get("bytes_in_use", 0))
        except Exception:
            return 0

    @property
    def concurrent_tasks(self) -> int:
        return self.conf.get_entry(CONCURRENT_TPU_TASKS)
