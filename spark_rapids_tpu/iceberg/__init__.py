"""Iceberg read path (reference: sql-plugin/.../iceberg + java iceberg
classes, ~8k LoC — SURVEY.md §2.8): table metadata JSON, manifest-list /
manifest Avro parsing, data-file scan through the engine's parquet
reader, positional + equality delete application (GpuDeleteFilter /
GpuIcebergReader / GpuMultiFileBatchReader analogs)."""

from spark_rapids_tpu.iceberg.metadata import (
    IcebergSnapshot,
    IcebergTableMetadata,
    load_table_metadata,
)
from spark_rapids_tpu.iceberg.scan import IcebergScanNode

__all__ = ["IcebergScanNode", "IcebergTableMetadata", "IcebergSnapshot",
           "load_table_metadata"]

from spark_rapids_tpu.overrides.rules import register_file_scan

register_file_scan(IcebergScanNode)
