"""Delta transaction log: actions, snapshot replay, checkpoints, commits.

Reference (SURVEY.md §2.8): the ``delta-lake/`` module family (35k LoC)
accelerates Delta Lake on the GPU — ``GpuOptimisticTransaction``,
``GpuDeltaLog``, checkpoint/snapshot machinery per Delta version. The TPU
build implements the Delta PROTOCOL natively (JSON commit files +
parquet checkpoints under ``_delta_log/``) against this engine's scan and
write paths, so tables it writes are plain Delta-shaped tables.

Log layout implemented:
- ``_delta_log/{version:020d}.json`` — newline-delimited action objects
  (``metaData``, ``add``, ``remove``, ``protocol``, ``commitInfo``).
- ``_delta_log/{version:020d}.checkpoint.parquet`` + ``_last_checkpoint``
  — flattened snapshot state for O(1) log replay startup.
- Commits are atomic via ``open(..., 'x')`` (fails if the version exists)
  which is the optimistic-concurrency primitive; losers re-read and retry
  (GpuOptimisticTransaction's commit loop)."""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import ColumnarProcessingError

LOG_DIR = "_delta_log"


class DeltaConcurrentModificationException(ColumnarProcessingError):
    """Lost the optimistic version race. Base class: retryable when the
    transaction is a blind append (the commit loop rebases); the typed
    subclasses below are TRUE conflicts that must surface."""


class DeltaMetadataChangedException(DeltaConcurrentModificationException):
    """A concurrent winner changed table metadata/protocol (schema
    evolution, property change, protocol upgrade) — staged actions read
    state that no longer holds; blind retry would revert the winner."""


class DeltaConcurrentWriteException(DeltaConcurrentModificationException):
    """A concurrent winner's file actions OVERLAP this transaction's
    (both touched existing files — DELETE/UPDATE/MERGE/overwrite vs
    anything, or colliding add paths); retrying the stale actions would
    silently lose the winner's changes."""


# -- schema JSON (Spark StructType JSON) -------------------------------------

_TYPE_TO_JSON = {
    T.BooleanType: "boolean", T.ByteType: "byte", T.ShortType: "short",
    T.IntegerType: "integer", T.LongType: "long", T.FloatType: "float",
    T.DoubleType: "double", T.StringType: "string", T.DateType: "date",
    T.TimestampType: "timestamp",
}
_JSON_TO_TYPE = {
    "boolean": T.BOOLEAN, "byte": T.BYTE, "short": T.SHORT,
    "integer": T.INT, "long": T.LONG, "float": T.FLOAT, "double": T.DOUBLE,
    "string": T.STRING, "date": T.DATE, "timestamp": T.TIMESTAMP,
}


def schema_to_json(schema: List[Tuple[str, T.DataType]]) -> str:
    fields = []
    for name, dt in schema:
        tj = _TYPE_TO_JSON.get(type(dt))
        if tj is None:
            raise ColumnarProcessingError(
                f"type {dt.simple_string()} not supported in delta schema")
        fields.append({"name": name, "type": tj, "nullable": True,
                       "metadata": {}})
    return json.dumps({"type": "struct", "fields": fields})


def schema_from_json(s: str) -> List[Tuple[str, T.DataType]]:
    obj = json.loads(s)
    out = []
    for f in obj["fields"]:
        t = f["type"]
        if not isinstance(t, str) or t not in _JSON_TO_TYPE:
            raise ColumnarProcessingError(
                f"delta schema type {t!r} not supported on this engine")
        out.append((f["name"], _JSON_TO_TYPE[t]))
    return out


def schema_fields_from_json(s: str) -> List[dict]:
    """Raw schema field dicts incl. per-field metadata (column-mapping
    physical names / ids live there — the Delta protocol's
    delta.columnMapping.physicalName key)."""
    return list(json.loads(s)["fields"])


# -- actions -----------------------------------------------------------------

@dataclass
class AddFile:
    path: str                      # relative to table root
    partition_values: Dict[str, Optional[str]]
    size: int
    modification_time: int
    data_change: bool = True
    stats: Optional[str] = None    # JSON: numRecords, minValues, maxValues
    deletion_vector: Optional[dict] = None

    def to_action(self) -> dict:
        a = {"path": self.path, "partitionValues": self.partition_values,
             "size": self.size, "modificationTime": self.modification_time,
             "dataChange": self.data_change}
        if self.stats is not None:
            a["stats"] = self.stats
        if self.deletion_vector is not None:
            a["deletionVector"] = self.deletion_vector
        return {"add": a}

    @property
    def num_records(self) -> Optional[int]:
        if self.stats:
            try:
                return json.loads(self.stats).get("numRecords")
            except (ValueError, AttributeError):
                return None
        return None


@dataclass
class RemoveFile:
    path: str
    deletion_timestamp: int
    data_change: bool = True

    def to_action(self) -> dict:
        return {"remove": {"path": self.path,
                           "deletionTimestamp": self.deletion_timestamp,
                           "dataChange": self.data_change}}


@dataclass
class SetTransaction:
    """The Delta protocol's ``txn`` action: an application-scoped
    watermark (appId -> monotonically increasing version) committed
    ATOMICALLY with the data it covers. THE exactly-once primitive for
    streaming sinks: a micro-batch's append commits
    ``txn(streamId, batchId)`` alongside its add actions, so a replay
    after a mid-write death reads the watermark back and skips the
    batch instead of double-appending (Structured Streaming's
    DeltaSink idempotency contract)."""

    app_id: str
    version: int
    last_updated: int = 0

    def to_action(self) -> dict:
        return {"txn": {"appId": self.app_id, "version": self.version,
                        "lastUpdated": self.last_updated
                        or int(time.time() * 1000)}}


@dataclass
class Metadata:
    schema_json: str
    partition_columns: List[str] = field(default_factory=list)
    table_id: str = ""
    name: Optional[str] = None
    configuration: Dict[str, str] = field(default_factory=dict)

    def to_action(self) -> dict:
        return {"metaData": {
            "id": self.table_id, "name": self.name,
            "format": {"provider": "parquet", "options": {}},
            "schemaString": self.schema_json,
            "partitionColumns": self.partition_columns,
            "configuration": self.configuration,
            "createdTime": int(time.time() * 1000)}}

    def column_mapping_mode(self) -> str:
        return self.configuration.get("delta.columnMapping.mode", "none")

    def physical_names(self) -> Dict[str, str]:
        """logical -> physical column name map. Identity when the table
        has no column mapping (physical names ARE logical names then).
        Memoized — a scan calls this per file and the schema JSON parse
        is not free at 10k files (code-review r5)."""
        got = getattr(self, "_phys_cache", None)
        if got is None:
            got = {}
            for f in schema_fields_from_json(self.schema_json):
                md = f.get("metadata") or {}
                got[f["name"]] = md.get(
                    "delta.columnMapping.physicalName", f["name"])
            self._phys_cache = got
        return got

    def cdf_enabled(self) -> bool:
        return self.configuration.get(
            "delta.enableChangeDataFeed", "false").lower() == "true"


PROTOCOL_ACTION = {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}}


# -- snapshot ----------------------------------------------------------------

@dataclass
class Snapshot:
    version: int
    metadata: Optional[Metadata]
    files: List[AddFile]           # live files after replay

    @property
    def schema(self) -> List[Tuple[str, T.DataType]]:
        if self.metadata is None:
            raise ColumnarProcessingError("delta table has no metadata")
        return schema_from_json(self.metadata.schema_json)


def _log_dir(table_path: str) -> str:
    return os.path.join(table_path, LOG_DIR)


def _version_of(fname: str) -> Optional[int]:
    stem = fname.split(".")[0]
    return int(stem) if stem.isdigit() and len(stem) == 20 else None


class DeltaLog:
    """Per-table log accessor (GpuDeltaLog analog)."""

    def __init__(self, table_path: str):
        self.table_path = table_path
        self.log_path = _log_dir(table_path)

    def exists(self) -> bool:
        return os.path.isdir(self.log_path) and any(
            f.endswith(".json") for f in os.listdir(self.log_path))

    def latest_version(self) -> int:
        versions = [] if not os.path.isdir(self.log_path) else [
            v for f in os.listdir(self.log_path)
            if f.endswith(".json") and (v := _version_of(f)) is not None]
        if not versions:
            raise ColumnarProcessingError(
                f"no delta log at {self.log_path}")
        return max(versions)

    # -- checkpoints --------------------------------------------------------
    def _last_checkpoint(self) -> Optional[dict]:
        p = os.path.join(self.log_path, "_last_checkpoint")
        if not os.path.exists(p):
            return None
        try:
            with open(p) as f:
                return json.load(f)
        except ValueError:
            return None

    @staticmethod
    def _as_pv(pv) -> dict:
        """partitionValues may arrive as a dict (struct read) or a list of
        (key, value) tuples (parquet map type)."""
        if pv is None:
            return {}
        if isinstance(pv, dict):
            return pv
        return dict(pv)

    def _read_checkpoint(self, version: int) -> Tuple[Optional[Metadata],
                                                      Dict[str, AddFile]]:
        """Read a checkpoint in the SPEC schema (nested metaData/add
        structs — interoperates with real Delta readers/writers) or the
        engine's pre-round-4 flattened metaData_*/add_* form."""
        import pyarrow.parquet as pq
        path = os.path.join(self.log_path,
                            f"{version:020d}.checkpoint.parquet")
        t = pq.read_table(path)
        rows = t.to_pylist()
        meta = None
        adds: Dict[str, AddFile] = {}
        recognized = 0
        for r in rows:
            md = r.get("metaData")
            if md and md.get("schemaString"):
                recognized += 1
                meta = Metadata(
                    schema_json=md["schemaString"],
                    partition_columns=md.get("partitionColumns") or [],
                    table_id=md.get("id") or "",
                    name=md.get("name"),
                    configuration=self._as_pv(md.get("configuration")))
            a = r.get("add")
            if a and a.get("path"):
                recognized += 1
                dv = a.get("deletionVector")
                adds[a["path"]] = AddFile(
                    path=a["path"],
                    partition_values=self._as_pv(a.get("partitionValues")),
                    size=a.get("size") or 0,
                    modification_time=a.get("modificationTime") or 0,
                    data_change=bool(a.get("dataChange", True)),
                    stats=a.get("stats"),
                    deletion_vector=dv if dv and dv.get("storageType")
                    else None)
            # legacy flattened form
            if r.get("metaData_schemaString"):
                recognized += 1
                meta = Metadata(
                    schema_json=r["metaData_schemaString"],
                    partition_columns=json.loads(
                        r["metaData_partitionColumns"] or "[]"),
                    table_id=r.get("metaData_id") or "",
                    configuration=json.loads(
                        r.get("metaData_configuration") or "{}"))
            if r.get("add_path"):
                recognized += 1
                af = AddFile(
                    path=r["add_path"],
                    partition_values=json.loads(
                        r["add_partitionValues"] or "{}"),
                    size=r["add_size"] or 0,
                    modification_time=r["add_modificationTime"] or 0,
                    stats=r.get("add_stats"),
                    deletion_vector=json.loads(r["add_deletionVector"])
                    if r.get("add_deletionVector") else None)
                adds[af.path] = af
        if meta is None or recognized == 0:
            # schema-mismatched/foreign checkpoint: treating it as empty
            # would silently drop every pre-checkpoint AddFile (ADVICE r2)
            raise ValueError(
                f"unrecognized checkpoint schema at version {version}")
        return meta, adds

    def write_checkpoint(self, snapshot: Snapshot):
        """Single-file checkpoint in the SPEC's nested action schema
        (metaData/add/protocol structs, partitionValues as map<str,str>) +
        _last_checkpoint pointer — interoperable with real Delta readers
        (ADVICE r2; reference: delta PROTOCOL.md checkpoint schema)."""
        import pyarrow as pa
        import pyarrow.parquet as pq
        m = snapshot.metadata
        rows = [
            {"protocol": {"minReaderVersion": PROTOCOL_ACTION["protocol"][
                "minReaderVersion"],
                "minWriterVersion": PROTOCOL_ACTION["protocol"][
                "minWriterVersion"]},
             "metaData": None, "add": None},
            {"protocol": None, "add": None,
             "metaData": {
                 "id": m.table_id, "name": m.name,
                 "format": {"provider": "parquet", "options": []},
                 "schemaString": m.schema_json,
                 "partitionColumns": m.partition_columns,
                 "configuration": list(m.configuration.items()),
                 "createdTime": None}},
        ]
        for a in snapshot.files:
            dv = a.deletion_vector
            rows.append({"protocol": None, "metaData": None, "add": {
                "path": a.path,
                "partitionValues": list(a.partition_values.items()),
                "size": a.size,
                "modificationTime": a.modification_time,
                "dataChange": False,
                "stats": a.stats,
                "deletionVector": {
                    "storageType": dv["storageType"],
                    "pathOrInlineDv": dv["pathOrInlineDv"],
                    "offset": dv.get("offset", 0),
                    "sizeInBytes": dv.get("sizeInBytes", 0),
                    "cardinality": dv.get("cardinality", 0),
                } if dv else None}})
        dv_t = pa.struct([("storageType", pa.string()),
                          ("pathOrInlineDv", pa.string()),
                          ("offset", pa.int32()),
                          ("sizeInBytes", pa.int32()),
                          ("cardinality", pa.int64())])
        schema = pa.schema([
            ("protocol", pa.struct([("minReaderVersion", pa.int32()),
                                    ("minWriterVersion", pa.int32())])),
            ("metaData", pa.struct([
                ("id", pa.string()), ("name", pa.string()),
                ("format", pa.struct([("provider", pa.string()),
                                      ("options",
                                       pa.map_(pa.string(), pa.string()))])),
                ("schemaString", pa.string()),
                ("partitionColumns", pa.list_(pa.string())),
                ("configuration", pa.map_(pa.string(), pa.string())),
                ("createdTime", pa.int64())])),
            ("add", pa.struct([
                ("path", pa.string()),
                ("partitionValues", pa.map_(pa.string(), pa.string())),
                ("size", pa.int64()),
                ("modificationTime", pa.int64()),
                ("dataChange", pa.bool_()),
                ("stats", pa.string()),
                ("deletionVector", dv_t)])),
        ])
        table = pa.Table.from_pylist(rows, schema=schema)
        path = os.path.join(self.log_path,
                            f"{snapshot.version:020d}.checkpoint.parquet")
        pq.write_table(table, path)
        tmp = os.path.join(self.log_path, "_last_checkpoint.tmp")
        with open(tmp, "w") as f:
            json.dump({"version": snapshot.version, "size": len(rows)}, f)
        os.replace(tmp, os.path.join(self.log_path, "_last_checkpoint"))

    # -- replay -------------------------------------------------------------
    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        """Replay the log up to ``version`` (default: latest), starting
        from the newest usable checkpoint."""
        latest = self.latest_version()
        target = latest if version is None else version
        if target > latest:
            raise ColumnarProcessingError(
                f"version {target} does not exist (latest {latest})")

        meta: Optional[Metadata] = None
        adds: Dict[str, AddFile] = {}
        start = 0
        cp = self._last_checkpoint()
        if cp and cp.get("version", -1) <= target:
            try:
                meta, adds = self._read_checkpoint(cp["version"])
                start = cp["version"] + 1
            except (OSError, KeyError, ValueError):
                meta, adds, start = None, {}, 0

        for v in range(start, target + 1):
            p = os.path.join(self.log_path, f"{v:020d}.json")
            if not os.path.exists(p):
                raise ColumnarProcessingError(
                    f"delta log is missing version {v}")
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    action = json.loads(line)
                    if "metaData" in action:
                        md = action["metaData"]
                        meta = Metadata(
                            schema_json=md["schemaString"],
                            partition_columns=md.get("partitionColumns", []),
                            table_id=md.get("id", ""),
                            name=md.get("name"),
                            configuration=md.get("configuration", {}))
                    elif "add" in action:
                        a = action["add"]
                        adds[a["path"]] = AddFile(
                            path=a["path"],
                            partition_values=a.get("partitionValues", {}),
                            size=a.get("size", 0),
                            modification_time=a.get("modificationTime", 0),
                            data_change=a.get("dataChange", True),
                            stats=a.get("stats"),
                            deletion_vector=a.get("deletionVector"))
                    elif "remove" in action:
                        adds.pop(action["remove"]["path"], None)
        return Snapshot(target, meta, list(adds.values()))

    def last_txn_version(self, app_id: str) -> Optional[int]:
        """The newest committed ``txn`` watermark for ``app_id``, or
        None if the application never committed one. Walks the log
        newest-first so the common case (watermark in the tail) is
        O(1) commits; txn actions replay like any action, so a
        watermark is durable exactly when its data is."""
        try:
            latest = self.latest_version()
        except ColumnarProcessingError:
            return None
        best: Optional[int] = None
        for v in range(latest, -1, -1):
            try:
                actions = self.read_actions(v)
            except (FileNotFoundError, OSError):
                continue
            for a in actions:
                t = a.get("txn")
                if t and t.get("appId") == app_id:
                    best = int(t["version"])
                    break
            if best is not None:
                return best
        return None

    # -- commit -------------------------------------------------------------
    def read_actions(self, version: int) -> List[dict]:
        """The raw action objects of one committed version (conflict
        classification reads the winners' commits through this)."""
        p = os.path.join(self.log_path, f"{version:020d}.json")
        with open(p) as f:
            return [json.loads(line) for line in f if line.strip()]

    def commit(self, actions: List[dict], expected_version: int,
               op_name: str = "WRITE") -> int:
        """Atomically write version ``expected_version``; raises
        DeltaConcurrentModificationException if someone else won the race
        (optimistic concurrency — OptimisticTransaction.commit re-reads,
        classifies the conflict, and rebases blind appends)."""
        import uuid as _uuid

        from spark_rapids_tpu.runtime.faults import fault_point
        os.makedirs(self.log_path, exist_ok=True)
        payload = [{"commitInfo": {
            "timestamp": int(time.time() * 1000), "operation": op_name,
            "engineInfo": "spark-rapids-tpu"}}] + actions
        path = os.path.join(self.log_path, f"{expected_version:020d}.json")
        # 'race' here simulates losing the version race without a real
        # concurrent writer; 'crash' dies mid-commit (the version file
        # either fully exists or not at all)
        fault_point("delta.commit.race")
        # publish ATOMICALLY: the payload is fully written to a temp
        # name (never matching *.json, so log listings ignore it), then
        # os.link claims the version — exclusive like open('x') AND
        # content-complete at first visibility, so a concurrent loser's
        # conflict classification can never read an empty/truncated
        # winner commit
        tmp = os.path.join(self.log_path,
                           f"{expected_version:020d}.tmp-"
                           f"{_uuid.uuid4().hex[:8]}")
        try:
            with open(tmp, "w") as f:
                for a in payload:
                    f.write(json.dumps(a) + "\n")
            try:
                os.link(tmp, path)
            except FileExistsError:
                raise DeltaConcurrentModificationException(
                    f"concurrent commit at version {expected_version} "
                    f"of {self.table_path}")
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        # a committed table write stales cached service results over
        # THIS table (the result cache keys entries on the epoch vector
        # of the tables their plan read) — scoped, so a hot cache over
        # an unrelated table survives, and the per-table bump is the
        # incremental-MV refresh trigger (epoch listeners)
        from spark_rapids_tpu.service.result_cache import (
            bump_table_epoch,
            delta_table_id,
        )
        bump_table_epoch(
            delta_table_id(self.table_path),
            f"delta {op_name} v{expected_version} {self.table_path}")
        return expected_version

    def history(self) -> List[dict]:
        """commitInfo per version, newest first (DESCRIBE HISTORY)."""
        out = []
        for v in range(self.latest_version(), -1, -1):
            p = os.path.join(self.log_path, f"{v:020d}.json")
            if not os.path.exists(p):
                continue
            info = {"version": v}
            with open(p) as f:
                for line in f:
                    if line.strip():
                        a = json.loads(line)
                        if "commitInfo" in a:
                            info.update(a["commitInfo"])
                            break
            out.append(info)
        return out
