"""Decimal arithmetic tests (reference: DecimalUtils JNI +
DecimalArithmeticOverrides + decimal integration suites): two-limb device
kernels vs Python-int oracle, Spark precision/scale rules, overflow
nulls, casts, engine integration."""

import decimal as pydec

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops import decimal as D
from spark_rapids_tpu.ops.expr import col, lit


# -- two-limb kernels vs python ints -----------------------------------------

def test_i64_mul_to_i128_exact(session):
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    a = rng.integers(-(10**18), 10**18, 300, dtype=np.int64)
    b = rng.integers(-(10**18), 10**18, 300, dtype=np.int64)
    hi, lo = D.i64_mul_to_i128(jnp.asarray(a), jnp.asarray(b))
    hi = np.asarray(hi).astype(object)
    lo = np.asarray(lo).astype(object)
    got = [int(h) * (1 << 64) + int(l) for h, l in zip(hi, lo)]
    want = [int(x) * int(y) for x, y in zip(a, b)]
    assert got == want


@pytest.mark.parametrize("d", [1, 4, 9, 13, 18])
def test_i128_div_pow10_half_up(session, d):
    import jax.numpy as jnp
    rng = np.random.default_rng(d)
    a = rng.integers(-(10**18), 10**18, 200, dtype=np.int64)
    b = rng.integers(-(10**18), 10**18, 200, dtype=np.int64)
    hi, lo = D.i64_mul_to_i128(jnp.asarray(a), jnp.asarray(b))
    qhi, qlo = D.i128_div_pow10_half_up(hi, lo, d)
    got = [int(h) * (1 << 64) + int(l)
           for h, l in zip(np.asarray(qhi).astype(object),
                           np.asarray(qlo).astype(object))]
    m = 10 ** d
    for g, x, y in zip(got, a, b):
        v = int(x) * int(y)
        q, r = divmod(abs(v), m)
        if 2 * r >= m:
            q += 1
        want = -q if v < 0 else q
        assert g == want, (x, y, d, g, want)


def test_u128_div_u64_big(session):
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    nums = [int(x) for x in rng.integers(0, 10**18, 100, dtype=np.int64)]
    ups = [int(x) for x in rng.integers(0, 10**18, 100, dtype=np.int64)]
    divs = [int(x) for x in rng.integers(1 << 31, 1 << 62, 100,
                                         dtype=np.int64)]
    vals = [n * u for n, u in zip(nums, ups)]
    hi = jnp.asarray([v >> 64 for v in vals], dtype=jnp.uint64)
    lo = jnp.asarray([v & ((1 << 64) - 1) for v in vals], dtype=jnp.uint64)
    dd = jnp.asarray(divs, dtype=jnp.uint64)
    q, r = D._u128_div_u64_big(hi, lo, dd)
    for i, (v, m) in enumerate(zip(vals, divs)):
        assert int(np.asarray(q)[i]) == v // m, (i, v, m)
        assert int(np.asarray(r)[i]) == v % m


# -- result-type rules -------------------------------------------------------

def test_spark_result_type_rules():
    a, b = T.DecimalType(10, 2), T.DecimalType(8, 4)
    assert D.add_result_type(a, b) == T.DecimalType(13, 4)
    assert D.mul_result_type(a, b) == T.DecimalType(19, 6)
    # divide: s = max(6, 2+8+1)=11, p = 10-2+4+11 = 23
    assert D.div_result_type(a, b) == T.DecimalType(23, 11)
    # precision-loss adjustment kicks in past 38
    big = T.DecimalType(38, 10)
    r = D.mul_result_type(big, big)
    assert r.precision == 38


# -- engine integration ------------------------------------------------------

def _dec_df(s, values, ptype, n_batches=1):
    unscaled = np.array([int(v.scaleb(ptype.scale)) for v in values],
                        dtype=np.int64)
    return s.create_dataframe({"d": unscaled}, dtypes={"d": ptype})


def _pd(x):
    return pydec.Decimal(x)


def test_engine_decimal_add_mul_div(session, cpu_session):
    from tests.asserts import assert_runs_on_tpu
    # (6,2) keeps every result type within the decimal64 device tier:
    # add -> (7,2), mul -> (13,4), div-by-int-literal -> (17,13)
    ptype = T.DecimalType(6, 2)
    rng = np.random.default_rng(1)
    vals = [_pd(int(x)) / 100 for x in
            rng.integers(-10**5, 10**5, 2000)]

    def q(s):
        df = _dec_df(s, vals, ptype)
        return df.select(
            (col("d") + col("d")).alias("a"),
            (col("d") * col("d")).alias("m"),
            (col("d") / lit(100)).alias("q"))

    got = q(session).collect()
    want = q(cpu_session).collect()
    assert got == want  # decimals must be BIT-exact between paths
    assert_runs_on_tpu(q, session)
    # spot-check against python Decimal
    a0, m0, q0 = got[0]
    schema = dict(q(session).plan.output_schema())
    sm = schema["m"]
    d0 = vals[0]
    assert a0 == int((d0 + d0).scaleb(schema["a"].scale))
    want_m = (d0 * d0).quantize(
        pydec.Decimal(1).scaleb(-sm.scale), rounding=pydec.ROUND_HALF_UP)
    assert m0 == int(want_m.scaleb(sm.scale))


def test_engine_decimal_overflow_nulls(session, cpu_session):
    ptype = T.DecimalType(18, 0)
    big = 10 ** 17

    def q(s):
        df = s.create_dataframe(
            {"d": np.array([big, 5, -big], dtype=np.int64)},
            dtypes={"d": ptype})
        # d * d overflows decimal(38,0)-capped result for big values
        return df.select((col("d") * col("d")).alias("m"))

    got = q(session).collect()
    want = q(cpu_session).collect()
    assert got == want
    assert got[1][0] == 25
    # 10^34 fits decimal(37,0) -> on host path valid; device must agree
    # (both paths computed it identically above)


def test_engine_int_decimal_mixing(session, cpu_session):
    ptype = T.DecimalType(12, 3)

    def q(s):
        df = _dec_df(s, [_pd("1.250"), _pd("-7.125")], ptype)
        return df.select((col("d") + lit(2)).alias("a"),
                         (col("d") * lit(3)).alias("m"))

    assert q(session).collect() == q(cpu_session).collect()


def test_decimal_casts(session, cpu_session):
    src = T.DecimalType(10, 4)

    def q(s):
        df = _dec_df(s, [_pd("12.3456"), _pd("-0.5000"), _pd("99.9999")],
                     src)
        from spark_rapids_tpu.ops.cast import Cast
        return df.select(
            Cast(col("d"), T.DecimalType(8, 2)).alias("rescale"),
            Cast(col("d"), T.LONG).alias("l"),
            Cast(col("d"), T.DOUBLE).alias("f"))

    got = q(session).collect()
    want = q(cpu_session).collect()
    # decimal/integral results bit-exact; the double column is subject to
    # the axon emulated-f64 division ulp (same carve-out as splitF64)
    for g, w in zip(got, want):
        assert g[:2] == w[:2]
        assert abs(g[2] - w[2]) <= 1e-12 * max(1.0, abs(w[2]))
    assert got[0][:2] == (1235, 12)        # HALF_UP to 2dp; trunc to long
    assert abs(got[0][2] - 12.3456) < 1e-12
    assert got[1][0] == -50 and got[1][1] == 0
    assert got[2][0] == 10000              # 99.9999 -> 100.00


def test_decimal_to_from_string_cpu(cpu_session):
    from spark_rapids_tpu.ops.cast import Cast
    df = cpu_session.create_dataframe(
        {"s": np.array(["12.345", "-0.5", "oops", "1e2"], dtype=object)})
    rows = df.select(
        Cast(col("s"), T.DecimalType(10, 2)).alias("d")).collect()
    assert rows[0][0] == 1235   # HALF_UP at scale 2 (unscaled)
    assert rows[1][0] == -50
    assert rows[2][0] is None
    assert rows[3][0] == 10000  # 1e2 == 100.00

    back = cpu_session.create_dataframe(
        {"d": np.array([1235, -50], dtype=np.int64)},
        dtypes={"d": T.DecimalType(10, 2)})
    srows = back.select(Cast(col("d"), T.STRING).alias("s")).collect()
    assert srows == [("12.35",), ("-0.50",)]


def test_decimal_divide_by_zero_null(session, cpu_session):
    ptype = T.DecimalType(6, 2)

    def q(s):
        df = _dec_df(s, [_pd("4.00"), _pd("9.00")], ptype)
        return df.select((col("d") / (col("d") - col("d"))).alias("q"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got == [(None,), (None,)]


def test_p_gt_18_falls_back_but_correct(session, cpu_session):
    """Operands driving the result past decimal64 tag device fallback;
    the host path computes exactly (python ints)."""
    ptype = T.DecimalType(18, 6)

    def q(s):
        df = _dec_df(s, [_pd("123456789012.345678")], ptype)
        return df.select((col("d") * col("d")).alias("m"))

    # result type decimal(37, 12) > decimal64 -> CPU path both sessions
    got = q(session).collect()
    want = q(cpu_session).collect()
    assert got == want
    v = _pd("123456789012.345678")
    with pydec.localcontext() as ctx:
        ctx.prec = 50  # default 28-digit context would round the product
        assert got[0][0] == int((v * v).scaleb(12))


def test_unscaled_value_and_make_decimal(session, cpu_session):
    ptype = T.DecimalType(9, 3)

    def q(s):
        df = _dec_df(s, [_pd("1.500"), _pd("-2.250")], ptype)
        return df.select(D.UnscaledValue(col("d")).alias("u"))

    got = q(session).collect()
    assert got == q(cpu_session).collect() == [(1500,), (-2250,)]


def test_double_to_decimal_cast_rounds_half_up(cpu_session, session):
    from spark_rapids_tpu.ops.cast import Cast

    def q(s):
        df = s.create_dataframe({"f": np.array([2.5, 2.555, -1.005, np.inf])})
        return df.select(Cast(col("f"), T.DecimalType(10, 2)).alias("d"))

    rows = q(cpu_session).collect()
    assert rows[0][0] == 250    # 2.50
    assert rows[1][0] == 256    # HALF_UP, not truncation
    assert rows[2][0] == -101   # -1.01 (repr half-up on magnitude)
    assert rows[3][0] is None   # inf -> null
    # device session takes the CPU fallback for float->decimal but must
    # produce the same values
    assert q(session).collect() == rows


def test_decimal_mixed_with_double_promotes(session, cpu_session):
    ptype = T.DecimalType(8, 2)

    def q(s):
        df = _dec_df(s, [_pd("2.50"), _pd("-4.00")], ptype)
        return df.select((col("d") * lit(1.5)).alias("m"))

    got = q(session).collect()
    want = q(cpu_session).collect()
    for g, w in zip(got, want):
        assert abs(g[0] - w[0]) <= 1e-12 * max(1.0, abs(w[0]))
    assert abs(got[0][0] - 3.75) < 1e-12
    # result is DOUBLE (Spark: decimal x double -> double)
    assert dict(q(session).plan.output_schema())["m"] == T.DOUBLE


def test_decimal_remainder_and_pmod(session, cpu_session):
    ptype = T.DecimalType(8, 2)

    def q(s):
        df = _dec_df(s, [_pd("7.50"), _pd("-7.50")], ptype)
        return df.select((col("d") % lit(2)).alias("r"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == 150    # 1.50 (unscaled at scale 2)
    assert got[1][0] == -150   # Java %: dividend sign


def test_decimal_pmod_negative_dividend(session, cpu_session):
    from spark_rapids_tpu.ops.arithmetic import Pmod
    ptype = T.DecimalType(8, 2)

    def q(s):
        df = _dec_df(s, [_pd("-7.50"), _pd("7.50")], ptype)
        return df.select(Pmod(col("d"), lit(2)).alias("p"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == 50     # pmod(-7.5, 2) = 0.50
    assert got[1][0] == 150    # pmod(7.5, 2) = 1.50


def test_decimal_divided_by_double_promotes(session, cpu_session):
    ptype = T.DecimalType(8, 2)

    def q(s):
        df = _dec_df(s, [_pd("5.00")], ptype)
        return df.select((col("d") / lit(2.0)).alias("q"))

    got = q(session).collect()
    want = q(cpu_session).collect()
    assert abs(got[0][0] - 2.5) < 1e-12
    assert abs(got[0][0] - want[0][0]) <= 1e-12
    assert dict(q(session).plan.output_schema())["q"] == T.DOUBLE


def test_decimal_add_19_digit_boundary(session, cpu_session):
    """decimal(18,0) + decimal(18,0) -> decimal(19,0): 10^18 is a VALID
    19-digit value; device must not null it (review fix)."""
    ptype = T.DecimalType(18, 0)
    v = 10**18 - 1

    def q(s):
        df = s.create_dataframe({"d": np.array([v], dtype=np.int64)},
                                dtypes={"d": ptype})
        return df.select((col("d") + lit(1)).alias("a"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == 10**18


def test_mixed_scale_decimal_comparison(session, cpu_session):
    a = T.DecimalType(6, 2)

    def q(s):
        df = s.create_dataframe(
            {"x": np.array([150, 149, 151], dtype=np.int64),
             "y": np.array([1500, 1500, 1500], dtype=np.int64)},
            dtypes={"x": a, "y": T.DecimalType(8, 3)})
        return df.select((col("x") == col("y")).alias("eq"),
                         (col("x") < col("y")).alias("lt"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got == [(True, False), (False, True), (False, False)]


def test_integral_divide_decimal(session, cpu_session):
    ptype = T.DecimalType(6, 1)

    def q(s):
        df = _dec_df(s, [_pd("7.5"), _pd("-7.5")], ptype)
        from spark_rapids_tpu.ops.arithmetic import IntegralDivide
        return df.select(
            IntegralDivide(col("d"), lit(5, T.DecimalType(2, 1))).alias("q"))

    got = q(session).collect()
    assert got == q(cpu_session).collect()
    assert got[0][0] == 15 and got[1][0] == -15  # 7.5 div 0.5
