"""Delta DML commands: DELETE, UPDATE, MERGE, OPTIMIZE (+Z-ORDER), VACUUM.

Reference (SURVEY.md §2.8): ``GpuDeleteCommand`` / ``GpuUpdateCommand`` /
``GpuMergeIntoCommand`` (+``GpuLowShuffleMergeCommand``), ``GpuOptimize``
/auto-compact, Z-ORDER via the zorder kernel, all inside
``GpuOptimisticTransaction`` commits.

TPU mapping kept per-file, like the reference's copy-on-write:
- DELETE: files whose every row matches are removed; partially-matched
  files get a deletion vector (merged with any existing one) — the
  deletion-vector write path.
- UPDATE: matched files are rewritten (surviving rows + updates applied).
- MERGE: equi-key merge — matched rows update/delete, unmatched source
  rows insert; touched target files rewrite.
- OPTIMIZE: bin-packs small files to the target size; ZORDER BY reorders
  rows by the interleaved-bits key before rewriting.
- VACUUM: removes data files no longer referenced by the latest snapshot.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.delta.log import (AddFile, DeltaLog, Metadata,
                                        RemoveFile, schema_fields_from_json)
from spark_rapids_tpu.delta.table import (
    DeltaScanNode,
    OptimisticTransaction,
    _mask_table,
    _write_data_file,
    read_dv,
    write_dv_file,
)
from spark_rapids_tpu.delta.zorder import zorder_sort_indexes
from spark_rapids_tpu.ops.expr import Expression, bind


def _cast_col(col: HostColumn, dt) -> HostColumn:
    if col.dtype.simple_string() == dt.simple_string():
        return col
    from spark_rapids_tpu.ops.cast import _cast_data_np
    return HostColumn(dt, _cast_data_np(col.data, col.dtype, dt),
                      col.validity)


def _read_physical(table_path: str, add: AddFile, schema,
                   physical: Optional[Dict[str, str]] = None) -> HostTable:
    """One data file's PHYSICAL rows (no DV applied) as the TABLE data
    schema — delegates to the single shared reader (table.py
    read_physical_parquet). ``physical``: logical->physical name map when
    the table uses column mapping."""
    from spark_rapids_tpu.delta.table import read_physical_parquet
    return read_physical_parquet(os.path.join(table_path, add.path),
                                 schema, physical)


from spark_rapids_tpu.delta.table import attach_partition_columns as \
    _with_partitions  # shared with the scan path

# -- change data feed --------------------------------------------------------
#: cdc files land here (Delta protocol _change_data/ + cdc actions)
CDF_DIR = "_change_data"


def _cdc_rows(full_table: HostTable, mask: np.ndarray,
              change_type: str) -> HostTable:
    """Selected rows + the protocol's _change_type column."""
    sub = _mask_table(full_table, mask)
    ct = HostColumn.from_pylist([change_type] * sub.num_rows,
                                T.StringType())
    return HostTable(list(sub.names) + ["_change_type"],
                     list(sub.columns) + [ct])


def _write_cdc_file(table_path: str, tables: List[HostTable],
                    physical: Optional[Dict[str, str]] = None
                    ) -> Optional[dict]:
    """One cdc parquet under _change_data/ + its raw ``cdc`` log action
    (reference: delta's AddCDCFile; GpuDeltaCatalog handles these through
    the same commitLarge path as adds). The engine writes FULL logical
    rows (incl. partition columns) into the cdc file — simpler than the
    protocol's partitionValues split and round-trips through
    table_changes exactly."""
    import uuid as _uuid

    import pyarrow.parquet as pq

    from spark_rapids_tpu.io.arrow_convert import host_table_to_arrow
    tables = [t for t in tables if t.num_rows]
    if not tables:
        return None
    table = HostTable.concat(tables) if len(tables) > 1 else tables[0]
    os.makedirs(os.path.join(table_path, CDF_DIR), exist_ok=True)
    rel = os.path.join(CDF_DIR, f"cdc-{_uuid.uuid4().hex}.parquet")
    full = os.path.join(table_path, rel)
    if physical:
        table = HostTable([physical.get(n, n) for n in table.names],
                          list(table.columns))
    pq.write_table(host_table_to_arrow(table), full)
    return {"cdc": {"path": rel, "partitionValues": {},
                    "size": os.path.getsize(full), "dataChange": False}}


class DeltaTable:
    """User API (io.delta.tables.DeltaTable analog)."""

    def __init__(self, session, table_path: str):
        self.session = session
        self.table_path = table_path
        self.log = DeltaLog(table_path)
        if not self.log.exists():
            raise ColumnarProcessingError(
                f"{table_path} is not a delta table")

    # -- read ----------------------------------------------------------------
    def to_df(self, version_as_of: Optional[int] = None):
        from spark_rapids_tpu.plan.dataframe import DataFrame
        return DataFrame(
            DeltaScanNode(self.table_path, self.session.conf,
                          version_as_of=version_as_of), self.session)

    def history(self) -> List[dict]:
        return self.log.history()

    def version(self) -> int:
        return self.log.latest_version()

    # -- shared helpers ------------------------------------------------------
    @staticmethod
    def _phys(snap) -> Optional[Dict[str, str]]:
        """logical->physical map when the table uses column mapping."""
        m = snap.metadata
        if m is None or m.column_mapping_mode() == "none":
            return None
        return m.physical_names()

    def _ctx(self):
        snap = self.log.snapshot()
        parts = set(snap.metadata.partition_columns)
        data_schema = [(n, dt) for n, dt in snap.schema if n not in parts]
        part_schema = [(n, dt) for n, dt in snap.schema if n in parts]
        return snap, data_schema, part_schema

    def _eval_mask(self, cond: Expression, table: HostTable) -> np.ndarray:
        bound = bind(cond, table.schema())
        res = bound.eval_cpu(table)
        return np.asarray(res.data, dtype=bool) & res.validity

    # -- DELETE --------------------------------------------------------------
    def delete(self, condition: Optional[Expression] = None) -> dict:
        """Returns {"num_affected_rows": N}; deletion-vector write path
        for partial files (GpuDeleteCommand + DV support)."""
        snap, data_schema, part_schema = self._ctx()
        pmap = self._phys(snap)
        cdf = snap.metadata.cdf_enabled()
        cdc_tables: List[HostTable] = []
        txn = OptimisticTransaction(self.log, self.session.conf,
                                    read_version=snap.version)
        now = int(time.time() * 1000)
        affected = 0
        for add in snap.files:
            if condition is None and not cdf:
                n = add.num_records
                if n is None:
                    n = _read_physical(self.table_path, add,
                                       data_schema, physical=pmap).num_rows
                if add.deletion_vector:
                    # stats count PHYSICAL rows; already-deleted ones are
                    # not affected by this delete
                    n -= add.deletion_vector.get("cardinality", 0)
                affected += max(n, 0)
                txn.stage(RemoveFile(add.path, now))
                continue
            phys = _read_physical(self.table_path, add, data_schema,
                                  physical=pmap)
            full = _with_partitions(phys, add, part_schema)
            matched = (np.ones(phys.num_rows, dtype=bool)
                       if condition is None
                       else self._eval_mask(condition, full))
            already = np.zeros(phys.num_rows, dtype=bool)
            if add.deletion_vector:
                dv = read_dv(self.table_path, add.deletion_vector)
                already[dv[dv < phys.num_rows]] = True
            new_hits = matched & ~already
            if not new_hits.any():
                continue
            affected += int(new_hits.sum())
            if cdf:
                cdc_tables.append(_cdc_rows(full, new_hits, "delete"))
            total = already | matched
            if total.all():
                txn.stage(RemoveFile(add.path, now))
            else:
                desc = write_dv_file(self.table_path,
                                     np.flatnonzero(total).astype(np.int64))
                txn.stage(RemoveFile(add.path, now, data_change=False))
                txn.stage(AddFile(
                    path=add.path, partition_values=add.partition_values,
                    size=add.size, modification_time=now,
                    data_change=False, stats=add.stats,
                    deletion_vector=desc))
        if cdf:
            cdc = _write_cdc_file(self.table_path, cdc_tables, pmap)
            if cdc is not None:
                txn.stage(cdc)
        if txn.actions:
            txn.commit("DELETE")
        return {"num_affected_rows": affected}

    # -- UPDATE --------------------------------------------------------------
    def update(self, condition: Optional[Expression],
               set: Dict[str, Expression]) -> dict:  # noqa: A002
        """Copy-on-write rewrite of matched files (GpuUpdateCommand)."""
        snap, data_schema, part_schema = self._ctx()
        part_names = {n for n, _ in part_schema}
        for c in set:
            if c in part_names:
                raise ColumnarProcessingError(
                    f"cannot UPDATE partition column {c!r}")
        pmap = self._phys(snap)
        cdf = snap.metadata.cdf_enabled()
        cdc_tables: List[HostTable] = []
        txn = OptimisticTransaction(self.log, self.session.conf,
                                    read_version=snap.version)
        now = int(time.time() * 1000)
        affected = 0
        for add in snap.files:
            phys = _read_physical(self.table_path, add, data_schema,
                                  physical=pmap)
            live = np.ones(phys.num_rows, dtype=bool)
            if add.deletion_vector:
                dv = read_dv(self.table_path, add.deletion_vector)
                live[dv[dv < phys.num_rows]] = False
            full = _with_partitions(phys, add, part_schema)
            matched = (np.ones(phys.num_rows, dtype=bool)
                       if condition is None
                       else self._eval_mask(condition, full)) & live
            if not matched.any():
                continue
            affected += int(matched.sum())
            # apply updates to matched rows over the LIVE subset
            out_cols = []
            schema = full.schema()
            for name, col in zip(full.names, full.columns):
                if name in set:
                    val = _cast_col(bind(set[name], schema).eval_cpu(full),
                                    col.dtype)
                    data = col.data.copy()
                    data[matched] = val.data[matched]
                    validity = np.where(matched, val.validity, col.validity)
                    out_cols.append(HostColumn(col.dtype, data, validity))
                else:
                    out_cols.append(col)
            updated = HostTable(list(full.names), out_cols)
            if cdf:
                cdc_tables.append(_cdc_rows(full, matched,
                                            "update_preimage"))
                cdc_tables.append(_cdc_rows(updated, matched,
                                            "update_postimage"))
            survivors = _mask_table(updated, live)
            data_only = HostTable(
                [n for n, _ in data_schema],
                [survivors.columns[list(survivors.names).index(n)]
                 for n, _ in data_schema])
            new_add = _write_data_file(
                self.table_path, data_only, add.partition_values,
                os.path.dirname(add.path), physical=pmap)
            txn.stage(RemoveFile(add.path, now), new_add)
        if cdf:
            cdc = _write_cdc_file(self.table_path, cdc_tables, pmap)
            if cdc is not None:
                txn.stage(cdc)
        if txn.actions:
            txn.commit("UPDATE")
        return {"num_affected_rows": affected}

    # -- MERGE ---------------------------------------------------------------
    def merge(self, source_df, on: Sequence[str]) -> "MergeBuilder":
        return MergeBuilder(self, source_df, list(on))

    # -- table properties / metadata commands --------------------------------
    def set_properties(self, props: Dict[str, str]) -> int:
        """Metadata-only commit updating table configuration (ALTER TABLE
        SET TBLPROPERTIES — how delta.enableChangeDataFeed turns on)."""
        snap = self.log.snapshot()
        m = snap.metadata
        cfg = dict(m.configuration)
        cfg.update(props)
        txn = OptimisticTransaction(self.log, self.session.conf,
                                    read_version=snap.version)
        txn.stage(Metadata(m.schema_json, m.partition_columns,
                           table_id=m.table_id, name=m.name,
                           configuration=cfg))
        return txn.commit("SET TBLPROPERTIES")

    def rename_column(self, old: str, new: str) -> int:
        """Rename WITHOUT rewriting any data file — the headline feature
        of Delta column mapping (reference: delta-lake column mapping
        support; GpuDeltaLog keeps the physical name in field metadata).
        First rename upgrades the table to columnMapping.mode=name,
        pinning every field's physicalName to its current name so
        existing files keep resolving."""
        snap = self.log.snapshot()
        m = snap.metadata
        fields = schema_fields_from_json(m.schema_json)
        if old not in [f["name"] for f in fields]:
            raise ColumnarProcessingError(
                f"no column {old!r} in {[f['name'] for f in fields]}")
        if new in [f["name"] for f in fields]:
            raise ColumnarProcessingError(f"column {new!r} already exists")
        if old in m.partition_columns:
            # existing AddFile.partitionValues are keyed by the current
            # name; renaming would null every old file's partition values
            raise ColumnarProcessingError(
                f"cannot rename partition column {old!r} (partitionValues "
                f"in the log are keyed by it)")
        cfg = dict(m.configuration)
        upgrading = m.column_mapping_mode() == "none"
        for i, f in enumerate(fields):
            md = dict(f.get("metadata") or {})
            if upgrading:
                md.setdefault("delta.columnMapping.physicalName", f["name"])
                md.setdefault("delta.columnMapping.id", i + 1)
            f["metadata"] = md
        if upgrading:
            cfg["delta.columnMapping.mode"] = "name"
            cfg["delta.columnMapping.maxColumnId"] = str(len(fields))
        for f in fields:
            if f["name"] == old:
                f["name"] = new
        parts = [new if c == old else c for c in m.partition_columns]
        schema_json = json.dumps({"type": "struct", "fields": fields})
        txn = OptimisticTransaction(self.log, self.session.conf,
                                    read_version=snap.version)
        if upgrading:
            # column mapping requires reader 2 / writer 5 per the protocol
            txn.stage({"protocol": {"minReaderVersion": 2,
                                    "minWriterVersion": 5}})
        txn.stage(Metadata(schema_json, parts, table_id=m.table_id,
                           name=m.name, configuration=cfg))
        return txn.commit("RENAME COLUMN")

    # -- change data feed reader ---------------------------------------------
    def table_changes(self, starting_version: int,
                      ending_version: Optional[int] = None):
        """DataFrame of row-level changes between versions (inclusive):
        table schema + _change_type + _commit_version. Commits carrying
        cdc actions read those files; plain add/remove commits derive
        insert/delete rows from the data files themselves (the Delta
        CDF read contract)."""
        import pyarrow.parquet as pq

        from spark_rapids_tpu.delta.table import _null_column
        from spark_rapids_tpu.io.arrow_convert import decode_to_schema
        from spark_rapids_tpu.plan import from_host_table
        latest = self.log.latest_version()
        end = latest if ending_version is None else min(ending_version,
                                                       latest)
        # parse the range's commit jsons ONCE; the CDF pre-check and the
        # change-derivation loop below share them (snapshot(v) per
        # version replays the whole log each time — O(V^2) in history)
        version_actions: List[Tuple[int, list]] = []
        for v in range(starting_version, end + 1):
            path = os.path.join(self.log.log_path, f"{v:020d}.json")
            if not os.path.exists(path):
                continue
            with open(path) as f:
                version_actions.append(
                    (v, [json.loads(line) for line in f if line.strip()]))
        # Delta CDF contract: versions where delta.enableChangeDataFeed
        # was not set have no recorded change data. Deriving them from
        # add/remove actions invents changes — a deletion-vector partial
        # DELETE would surface every physical row of the file as
        # 'delete', survivors included — so the whole range must be
        # covered by the feed (DeltaErrors.changeDataNotRecorded). One
        # snapshot seeds the flag; metaData actions inside the range
        # update it forward.
        cdf_on = self.log.snapshot(
            min(starting_version, end)).metadata.cdf_enabled()
        for v, actions in version_actions:
            for a in actions:
                if "metaData" in a:
                    cfg = a["metaData"].get("configuration") or {}
                    cdf_on = cfg.get("delta.enableChangeDataFeed",
                                     "false").lower() == "true"
            if not cdf_on:
                raise ColumnarProcessingError(
                    f"change data was not recorded for version {v} "
                    f"(requested range [{starting_version}, {end}]): "
                    "delta.enableChangeDataFeed was not set; changes "
                    "are only readable from the version that enabled it")
        snap = self.log.snapshot(end)
        pmap = self._phys(snap)
        parts = set(snap.metadata.partition_columns)
        schema = snap.schema
        data_schema = [(n, dt) for n, dt in schema if n not in parts]
        part_schema = [(n, dt) for n, dt in schema if n in parts]
        out: List[HostTable] = []

        def _with_meta(tbl: HostTable, version: int,
                       change_type: Optional[str]) -> HostTable:
            names = list(tbl.names)
            cols = list(tbl.columns)
            if change_type is not None:
                names.append("_change_type")
                cols.append(HostColumn.from_pylist(
                    [change_type] * tbl.num_rows, T.StringType()))
            names.append("_commit_version")
            cols.append(HostColumn(T.LongType(), np.full(
                tbl.num_rows, version, dtype=np.int64)))
            return HostTable(names, cols)

        def _read_data_file(rel: str, pv: Dict[str, str]) -> HostTable:
            add = AddFile(path=rel, partition_values=pv, size=0,
                          modification_time=0)
            tbl = _read_physical(self.table_path, add, data_schema,
                                 physical=pmap)
            tbl = _with_partitions(tbl, add, part_schema)
            # SCHEMA order, matching the cdc branch — HostTable.concat is
            # positional (code-review r5)
            by = dict(zip(tbl.names, tbl.columns))
            order = [n for n, _ in schema]
            return HostTable(order, [by[n] for n in order])

        for v, actions in version_actions:
            cdcs = [a["cdc"] for a in actions if "cdc" in a]
            if cdcs:
                from spark_rapids_tpu.delta.table import \
                    read_physical_parquet
                cdc_schema = list(schema) + [("_change_type",
                                             T.StringType())]
                for c in cdcs:
                    tbl = read_physical_parquet(
                        os.path.join(self.table_path, c["path"]),
                        cdc_schema, pmap)
                    out.append(_with_meta(tbl, v, None))
                continue
            for a in actions:
                if "add" in a and a["add"].get("dataChange", True):
                    out.append(_with_meta(
                        _read_data_file(a["add"]["path"],
                                        a["add"].get("partitionValues",
                                                     {})), v, "insert"))
                elif "remove" in a and a["remove"].get("dataChange", True):
                    rel = a["remove"]["path"]
                    if os.path.exists(os.path.join(self.table_path, rel)):
                        out.append(_with_meta(
                            _read_data_file(
                                rel, a["remove"].get("partitionValues",
                                                     {})), v, "delete"))
        if not out:
            empty = HostTable(
                [n for n, _ in schema] + ["_change_type",
                                          "_commit_version"],
                [_null_column(dt, 0) for _, dt in schema]
                + [HostColumn.from_pylist([], T.StringType()),
                   HostColumn(T.LongType(), np.array([], np.int64))])
            return from_host_table(empty, self.session)
        res = HostTable.concat(out) if len(out) > 1 else out[0]
        return from_host_table(res, self.session)

    # -- OPTIMIZE ------------------------------------------------------------
    def optimize(self, zorder_by: Optional[Sequence[str]] = None,
                 target_file_size: int = 128 << 20) -> dict:
        """Bin-pack small files; with zorder_by, rewrite ALL files in
        z-order (GpuOptimize / Z-ORDER BY)."""
        snap, data_schema, part_schema = self._ctx()
        txn = OptimisticTransaction(self.log, self.session.conf,
                                    read_version=snap.version)
        now = int(time.time() * 1000)
        # group files by partition (optimize never crosses partitions)
        groups: Dict[tuple, List[AddFile]] = {}
        for add in snap.files:
            key = tuple(sorted(add.partition_values.items()))
            groups.setdefault(key, []).append(add)
        removed = added = 0
        for key, adds in groups.items():
            if zorder_by is None:
                small = [a for a in adds if a.size < target_file_size]
                if len(small) < 2:
                    continue
                batch = small
            else:
                batch = adds
                if not batch:
                    continue
            tables = []
            pmap = self._phys(snap)
            for a in batch:
                phys = _read_physical(self.table_path, a, data_schema,
                                      physical=pmap)
                live = np.ones(phys.num_rows, dtype=bool)
                if a.deletion_vector:
                    dv = read_dv(self.table_path, a.deletion_vector)
                    live[dv[dv < phys.num_rows]] = False
                tables.append(_mask_table(phys, live))
            merged = HostTable.concat(tables) if len(tables) > 1 \
                else tables[0]
            if zorder_by is not None:
                zcols = [c for c in zorder_by
                         if c in [n for n, _ in data_schema]]
                if zcols:
                    order = zorder_sort_indexes(merged, zcols)
                    merged = _mask_permute(merged, order)
            pv = dict(key)
            subdir = os.path.dirname(batch[0].path)
            new_add = _write_data_file(self.table_path, merged, pv, subdir,
                                       physical=pmap)
            for a in batch:
                txn.stage(RemoveFile(a.path, now, data_change=False))
            new_add.data_change = False
            txn.stage(new_add)
            removed += len(batch)
            added += 1
        if txn.actions:
            txn.commit("OPTIMIZE" if zorder_by is None
                       else "OPTIMIZE ZORDER")
        return {"files_removed": removed, "files_added": added}

    # -- VACUUM --------------------------------------------------------------
    def vacuum(self, dry_run: bool = False,
               retention_hours: Optional[float] = None) -> dict:
        """Delete data files not referenced by the LATEST snapshot.
        ``dry_run`` reports the orphans without touching them;
        ``retention_hours`` (default: the
        ``spark.rapids.delta.vacuum.retentionHours`` conf) keeps
        orphans younger than the window — a concurrent uncommitted
        transaction may still be about to commit them."""
        return vacuum_table(self.table_path, conf=self.session.conf,
                            dry_run=dry_run,
                            retention_hours=retention_hours)


def vacuum_table(table_path: str, conf=None, dry_run: bool = False,
                 retention_hours: Optional[float] = None) -> dict:
    """VACUUM over a Delta table directory: every file not referenced
    by the latest snapshot (data files, resolved deletion-vector files)
    is an orphan — leftovers of overwritten versions, failed/conflicted
    transactions, or jobs that died mid-write. ``tools vacuum`` and
    :meth:`DeltaTable.vacuum` share this implementation; no session
    needed."""
    from spark_rapids_tpu.conf import RapidsConf
    from spark_rapids_tpu.delta.table import _dv_relative_path
    from spark_rapids_tpu.io.committer import (
        DELTA_VACUUM_RETENTION_HOURS,
        WRITE_METRICS,
        unlink_and_prune,
        vacuum_protection,
    )
    conf = conf if conf is not None else RapidsConf()
    if retention_hours is None:
        retention_hours = float(
            conf.get_entry(DELTA_VACUUM_RETENTION_HOURS))
    log = DeltaLog(table_path)
    snap = log.snapshot()
    live = {a.path for a in snap.files}
    for a in snap.files:
        dv = a.deletion_vector
        if not dv:
            continue
        # resolve the descriptor to the ON-DISK relative path ('u'
        # storage encodes a base85 uuid, not a filename — matching the
        # raw pathOrInlineDv would sweep every live DV file)
        st = dv.get("storageType")
        if st == "u":
            live.add(_dv_relative_path(dv["pathOrInlineDv"]))
        elif st == "p":
            p = dv["pathOrInlineDv"]
            if not os.path.isabs(p):
                live.add(p)
    protected = vacuum_protection(table_path, retention_hours)
    orphans: List[str] = []
    for root, dirs, files in os.walk(table_path):
        dirs[:] = [d for d in dirs if d != "_delta_log"]
        for f in sorted(files):
            full = os.path.join(root, f)
            rel = os.path.relpath(full, table_path)
            if rel.startswith(CDF_DIR):
                # cdc files are owned by the change feed, not the
                # snapshot; without a retention clock vacuum leaves
                # them for table_changes
                continue
            if rel in live or protected(full):
                continue
            orphans.append(rel)
    deleted = 0
    if not dry_run:
        deleted = unlink_and_prune(table_path, orphans,
                                   keep_dirs=("_delta_log", CDF_DIR))
        if deleted:
            WRITE_METRICS.add("vacuumedFiles", deleted)
    return {"files_deleted": deleted, "orphans": orphans,
            "dry_run": bool(dry_run),
            "retention_hours": retention_hours}


def _mask_permute(table: HostTable, order: np.ndarray) -> HostTable:
    cols = [HostColumn(c.dtype, c.data[order], c.validity[order])
            for c in table.columns]
    return HostTable(list(table.names), cols)


class MergeBuilder:
    """merge(source, on).when_matched_update(set=...)
    .when_matched_delete().when_not_matched_insert().execute()"""

    def __init__(self, table: DeltaTable, source_df, on: List[str]):
        self.table = table
        self.source_df = source_df
        self.on = on
        self._update_set: Optional[Dict[str, str]] = None
        self._delete = False
        self._insert = False

    def when_matched_update(self, set: Dict[str, str]):  # noqa: A002
        """set maps target column -> SOURCE column name."""
        if self._delete:
            raise ColumnarProcessingError(
                "cannot combine when_matched_update with "
                "when_matched_delete (unconditional clauses are ambiguous)")
        self._update_set = dict(set)
        return self

    def when_matched_delete(self):
        if self._update_set is not None:
            raise ColumnarProcessingError(
                "cannot combine when_matched_update with "
                "when_matched_delete (unconditional clauses are ambiguous)")
        self._delete = True
        return self

    def when_not_matched_insert(self):
        self._insert = True
        return self

    def execute(self) -> dict:
        t = self.table
        snap, data_schema, part_schema = t._ctx()
        if part_schema and self._insert:
            raise ColumnarProcessingError(
                "MERGE insert into partitioned tables is not supported yet")
        src = self.source_df.collect_table()
        src_names = list(src.names)
        for k in self.on:
            if k not in src_names:
                raise ColumnarProcessingError(
                    f"merge key {k!r} not in source {src_names}")
        import pandas as pd
        key_idx = [src_names.index(k) for k in self.on]
        # SQL null semantics: a NULL key never matches — exclude null-keyed
        # source rows from the probe side entirely
        src_valid = np.ones(src.num_rows, dtype=bool)
        for i in key_idx:
            src_valid &= src.columns[i].validity
        src_probe = pd.DataFrame(
            {k: src.columns[i].data[src_valid]
             for k, i in zip(self.on, key_idx)})
        src_probe["__src_row"] = np.flatnonzero(src_valid)
        if (self._update_set or self._delete) and \
                src_probe.duplicated(subset=self.on).any():
            # Delta semantics: a target row must not match multiple source
            # rows when matched-clauses exist
            raise ColumnarProcessingError(
                "MERGE source has multiple rows for at least one key "
                "(ambiguous matched-clause application)")

        from spark_rapids_tpu.conf import DELTA_LOW_SHUFFLE_MERGE
        low_shuffle = bool(
            t.session.conf.get_entry(DELTA_LOW_SHUFFLE_MERGE))
        pmap = t._phys(snap)
        cdf = snap.metadata.cdf_enabled()
        cdc_tables: List[HostTable] = []
        txn = OptimisticTransaction(t.log, t.session.conf,
                                    read_version=snap.version)
        now = int(time.time() * 1000)
        matched_rows = deleted_rows = rewritten_files = dv_files = 0
        matched_src: set = set()
        for add in snap.files:
            phys = _read_physical(t.table_path, add, data_schema,
                                  physical=pmap)
            live = np.ones(phys.num_rows, dtype=bool)
            if add.deletion_vector:
                dv = read_dv(t.table_path, add.deletion_vector)
                live[dv[dv < phys.num_rows]] = False
            full = _with_partitions(phys, add, part_schema)
            tgt_idx = [list(full.names).index(k) for k in self.on]
            tgt_valid = live.copy()
            for i in tgt_idx:
                tgt_valid &= full.columns[i].validity
            probe = pd.DataFrame(
                {k: full.columns[i].data[tgt_valid]
                 for k, i in zip(self.on, tgt_idx)})
            probe["__tgt_row"] = np.flatnonzero(tgt_valid)
            joined = probe.merge(src_probe, on=self.on, how="inner")
            hit = np.zeros(full.num_rows, dtype=np.int64) - 1
            hit[joined["__tgt_row"].to_numpy()] = \
                joined["__src_row"].to_numpy()
            matched_src.update(joined["__src_row"].tolist())
            matched = hit >= 0
            if not matched.any():
                continue
            matched_rows += int(matched.sum())
            if cdf and self._delete:
                cdc_tables.append(_cdc_rows(full, matched & live, "delete"))
            if self._delete:
                deleted_rows += int(matched.sum())
                keep = live & ~matched
            else:
                keep = live
            if low_shuffle and not (self._update_set or self._delete):
                # insert-only merge: matched target rows are untouched —
                # no file actions at all for this file
                continue
            if low_shuffle:
                # LOW-SHUFFLE path (GpuLowShuffleMergeCommand analog):
                # matched rows die via a deletion vector; updates write
                # ONLY the touched rows to a small file — untouched rows
                # of this file never rewrite
                dead = ~live | matched
                if dead.all():
                    txn.stage(RemoveFile(add.path, now))
                else:
                    desc = write_dv_file(
                        t.table_path,
                        np.flatnonzero(dead).astype(np.int64))
                    txn.stage(RemoveFile(add.path, now,
                                         data_change=False))
                    txn.stage(AddFile(
                        path=add.path,
                        partition_values=add.partition_values,
                        size=add.size, modification_time=now,
                        data_change=False, stats=add.stats,
                        deletion_vector=desc))
                    dv_files += 1
                if self._update_set and not self._delete:
                    rows = np.flatnonzero(matched)
                    upd_cols = []
                    for name, col in zip(full.names, full.columns):
                        if name in self._update_set:
                            sc = _cast_col(src.columns[src_names.index(
                                self._update_set[name])], col.dtype)
                            upd_cols.append(HostColumn(
                                col.dtype, sc.data[hit[rows]],
                                sc.validity[hit[rows]]))
                        else:
                            upd_cols.append(HostColumn(
                                col.dtype, col.data[rows],
                                col.validity[rows]))
                    upd = HostTable(list(full.names), upd_cols)
                    if cdf:
                        allm = np.ones(upd.num_rows, dtype=bool)
                        cdc_tables.append(_cdc_rows(
                            full, matched & live, "update_preimage"))
                        cdc_tables.append(_cdc_rows(upd, allm,
                                                    "update_postimage"))
                    data_only = HostTable(
                        [n for n, _ in data_schema],
                        [upd.columns[list(upd.names).index(n)]
                         for n, _ in data_schema])
                    txn.stage(_write_data_file(
                        t.table_path, data_only, add.partition_values,
                        os.path.dirname(add.path), physical=pmap))
                continue
            rewritten_files += 1
            out_cols = []
            for name, col in zip(full.names, full.columns):
                if (self._update_set and name in self._update_set
                        and not self._delete):
                    sc = src.columns[src_names.index(
                        self._update_set[name])]
                    data = col.data.copy()
                    validity = col.validity.copy()
                    rows = np.flatnonzero(matched)
                    data[rows] = sc.data[hit[rows]]
                    validity[rows] = sc.validity[hit[rows]]
                    out_cols.append(HostColumn(col.dtype, data, validity))
                else:
                    out_cols.append(col)
            full_updated = HostTable(list(full.names), out_cols)
            if cdf and self._update_set and not self._delete:
                cdc_tables.append(_cdc_rows(full, matched & live,
                                            "update_preimage"))
                cdc_tables.append(_cdc_rows(full_updated, matched & live,
                                            "update_postimage"))
            updated = _mask_table(full_updated, keep)
            data_only = HostTable(
                [n for n, _ in data_schema],
                [updated.columns[list(updated.names).index(n)]
                 for n, _ in data_schema])
            if data_only.num_rows:
                txn.stage(_write_data_file(
                    t.table_path, data_only, add.partition_values,
                    os.path.dirname(add.path), physical=pmap))
            txn.stage(RemoveFile(add.path, now))

        inserted = 0
        if self._insert:
            unmatched = [r for r in range(src.num_rows)
                         if r not in matched_src]
            if unmatched:
                mask = np.zeros(src.num_rows, dtype=bool)
                mask[unmatched] = True
                ins = _mask_table(src, mask)
                # project source to the target data schema by name
                cols = []
                for n, dt in data_schema:
                    if n not in src_names:
                        raise ColumnarProcessingError(
                            f"insert requires source column {n!r}")
                    cols.append(_cast_col(ins.columns[src_names.index(n)],
                                          dt))
                ins_table = HostTable([n for n, _ in data_schema], cols)
                txn.stage(_write_data_file(
                    t.table_path, ins_table, {}, physical=pmap))
                if cdf:
                    cdc_tables.append(_cdc_rows(
                        ins_table, np.ones(ins_table.num_rows, dtype=bool),
                        "insert"))
                inserted = len(unmatched)

        if cdf:
            cdc = _write_cdc_file(t.table_path, cdc_tables, pmap)
            if cdc is not None:
                txn.stage(cdc)
        if txn.actions:
            txn.commit("MERGE")
        return {"num_matched_rows": matched_rows,
                "num_deleted_rows": deleted_rows,
                "num_inserted_rows": inserted,
                "low_shuffle": low_shuffle,
                "num_rewritten_files": rewritten_files,
                "num_dv_files": dv_files}
