"""Packed host-table wire format ("TPAK").

Reference: JCudfSerialization (SURVEY.md §2.9 — "shuffle wire format:
header + packed host buffer", GpuColumnarBatchSerializer.scala:25-26).
Layout (little-endian):

  magic  b"TPAK"  | version u32 | ncols u32 | nrows u64
  per column header: name_len u16 + name utf8, dtype tag u8
                     (+ precision u8, scale u8 for decimal)
  per column body:   validity bitmask ceil(n/8) bytes, then
     fixed-width: raw array bytes (n * itemsize)
     string:      offsets int64[n+1] + utf8 blob (int64: blobs may pass 2GiB)
  footer (v2):       crc32 u32 over everything above

The format is self-describing so shuffle readers need no schema exchange.
A C++ implementation with the same layout is the planned native fast path.
Version 2 appends a CRC32 footer so a corrupted frame (bit flip on the
wire, torn file read, chaos-injected damage) surfaces as a RETRYABLE
CorruptFrameError instead of silently deserializing garbage — shuffle
blobs are ephemeral, so the version bump has no migration cost.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Tuple

import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.errors import CorruptFrameError

MAGIC = b"TPAK"
VERSION = 2

_TAGS = [
    (T.BooleanType, 1), (T.ByteType, 2), (T.ShortType, 3), (T.IntegerType, 4),
    (T.LongType, 5), (T.FloatType, 6), (T.DoubleType, 7), (T.StringType, 8),
    (T.DateType, 9), (T.TimestampType, 10), (T.NullType, 11),
    (T.DecimalType, 12),
]
_TAG_OF = {cls: tag for cls, tag in _TAGS}
_CLS_OF = {tag: cls for cls, tag in _TAGS}


def _dtype_of_tag(tag: int, extra: Tuple[int, int]) -> T.DataType:
    cls = _CLS_OF[tag]
    if cls is T.DecimalType:
        return T.DecimalType(extra[0], extra[1])
    return cls()


def pack_table(table: HostTable) -> bytes:
    out: List[bytes] = [MAGIC, struct.pack("<IIQ", VERSION, table.num_columns,
                                           table.num_rows)]
    n = table.num_rows
    for name, col in zip(table.names, table.columns):
        nb = name.encode("utf-8")
        tag = _TAG_OF[type(col.dtype)]
        out.append(struct.pack("<H", len(nb)))
        out.append(nb)
        if isinstance(col.dtype, T.DecimalType):
            out.append(struct.pack("<BBB", tag, col.dtype.precision, col.dtype.scale))
        else:
            out.append(struct.pack("<BBB", tag, 0, 0))
    for col in table.columns:
        out.append(np.packbits(col.validity.astype(np.uint8),
                               bitorder="little").tobytes())
        if isinstance(col.dtype, T.StringType):
            encoded = [(s.encode("utf-8") if s is not None and v else b"")
                       for s, v in zip(col.data, col.validity)]
            offsets = np.zeros(n + 1, dtype=np.int64)
            if n:
                offsets[1:] = np.cumsum([len(b) for b in encoded], dtype=np.int64)
            out.append(offsets.tobytes())
            out.append(b"".join(encoded))
        elif isinstance(col.dtype, T.NullType):
            pass  # validity only
        elif T.is_dec128(col.dtype):
            # fixed 16 bytes/row: two little-endian int64 limbs
            from spark_rapids_tpu.columnar.column import dec128_limbs
            limbs = dec128_limbs(col.data, col.validity, n)
            out.append(np.ascontiguousarray(limbs).tobytes())
        else:
            arr = np.ascontiguousarray(col.data, dtype=col.dtype.np_dtype)
            out.append(arr.tobytes())
    body = b"".join(out)
    return body + struct.pack("<I", zlib.crc32(body))


def unpack_table(buf: bytes, offset: int = 0) -> Tuple[HostTable, int]:
    """Returns (table, bytes consumed from offset). Integrity failures
    (bad magic/version, truncation, CRC mismatch) raise the RETRYABLE
    CorruptFrameError so the fetch-retry / recompute machinery recovers
    instead of the query dying on garbage bytes."""
    view = memoryview(buf)
    pos = offset
    try:
        if bytes(view[pos:pos + 4]) != MAGIC:
            raise CorruptFrameError("bad TPAK magic")
        pos += 4
        version, ncols, nrows = struct.unpack_from("<IIQ", view, pos)
        pos += 16
        if version != VERSION:
            raise CorruptFrameError(f"TPAK version {version}")
    except struct.error as e:
        raise CorruptFrameError(f"truncated TPAK header: {e}") from e
    try:
        names, dtypes, cols, pos = _unpack_body(view, pos, ncols, nrows)
    except (struct.error, ValueError, KeyError, UnicodeDecodeError) as e:
        raise CorruptFrameError(f"corrupt TPAK frame: {e}") from e
    try:
        (stored_crc,) = struct.unpack_from("<I", view, pos)
    except struct.error as e:
        raise CorruptFrameError("TPAK frame missing CRC footer") from e
    if zlib.crc32(view[offset:pos]) != stored_crc:
        raise CorruptFrameError("TPAK CRC mismatch (corrupt frame)")
    pos += 4
    return HostTable(names, cols), pos - offset


def _unpack_body(view: memoryview, pos: int, ncols: int, nrows: int):
    names: List[str] = []
    dtypes: List[T.DataType] = []
    for _ in range(ncols):
        (nlen,) = struct.unpack_from("<H", view, pos)
        pos += 2
        names.append(bytes(view[pos:pos + nlen]).decode("utf-8"))
        pos += nlen
        tag, p, s = struct.unpack_from("<BBB", view, pos)
        pos += 3
        dtypes.append(_dtype_of_tag(tag, (p, s)))
    cols: List[HostColumn] = []
    vbytes = (nrows + 7) // 8
    for dt in dtypes:
        validity = np.unpackbits(
            np.frombuffer(view, dtype=np.uint8, count=vbytes, offset=pos),
            bitorder="little")[:nrows].astype(np.bool_)
        pos += vbytes
        if isinstance(dt, T.StringType):
            offsets = np.frombuffer(view, dtype=np.int64, count=nrows + 1,
                                    offset=pos)
            pos += offsets.nbytes
            blob_len = int(offsets[-1]) if nrows else 0
            blob = bytes(view[pos:pos + blob_len])
            pos += blob_len
            data = np.empty(nrows, dtype=object)
            for i in range(nrows):
                if validity[i]:
                    data[i] = blob[offsets[i]:offsets[i + 1]].decode("utf-8")
                else:
                    data[i] = None
            cols.append(HostColumn(dt, data, validity))
        elif isinstance(dt, T.NullType):
            cols.append(HostColumn(dt, np.zeros(nrows, dtype=np.int8), validity))
        elif T.is_dec128(dt):
            from spark_rapids_tpu.columnar.column import dec128_unscaled
            limbs = np.frombuffer(view, dtype=np.int64, count=2 * nrows,
                                  offset=pos).reshape(nrows, 2)
            pos += int(nrows) * 16
            cols.append(HostColumn(dt, dec128_unscaled(limbs, validity),
                                   validity))
        else:
            np_dt = dt.np_dtype
            data = np.frombuffer(view, dtype=np_dt, count=nrows, offset=pos).copy()
            pos += int(nrows) * np_dt.itemsize
            cols.append(HostColumn(dt, data, validity))
    return names, dtypes, cols, pos
