"""PySpark-style function namespace (the user-facing expression builders)."""

from __future__ import annotations

from spark_rapids_tpu.ops.expr import col, lit, Expression  # noqa: F401
from spark_rapids_tpu.ops import aggregates as _agg
from spark_rapids_tpu.ops import conditional as _cond
from spark_rapids_tpu.ops import math as _math
from spark_rapids_tpu.ops import predicates as _pred


def _e(x) -> Expression:
    return x if isinstance(x, Expression) else col(x) if isinstance(x, str) else lit(x)


# aggregates
def sum(e):  # noqa: A001
    return _agg.Sum(_e(e))


def min(e):  # noqa: A001
    return _agg.Min(_e(e))


def max(e):  # noqa: A001
    return _agg.Max(_e(e))


def count(e="*"):
    if e == "*" or e == 1:
        return _agg.Count()
    return _agg.Count(_e(e))


def avg(e):
    return _agg.Average(_e(e))


mean = avg


def first(e, ignore_nulls=False):
    return _agg.First(_e(e), ignore_nulls)


def last(e, ignore_nulls=False):
    return _agg.Last(_e(e), ignore_nulls)


def stddev(e):
    return _agg.StddevSamp(_e(e))


def stddev_pop(e):
    return _agg.StddevPop(_e(e))


def variance(e):
    return _agg.VarianceSamp(_e(e))


def var_pop(e):
    return _agg.VariancePop(_e(e))


# conditionals
def when(cond, value):
    return WhenBuilder().when(cond, value)


class WhenBuilder:
    def __init__(self):
        self._branches = []

    def when(self, cond, value):
        self._branches.extend([_e(cond), _e(value)])
        return self

    def otherwise(self, value):
        return _cond.CaseWhen(*self._branches, _e(value))

    def end(self):
        return _cond.CaseWhen(*self._branches)


def coalesce(*exprs):
    return _cond.Coalesce(*[_e(e) for e in exprs])


def greatest(*exprs):
    return _cond.Greatest(*[_e(e) for e in exprs])


def least(*exprs):
    return _cond.Least(*[_e(e) for e in exprs])


def nanvl(a, b):
    return _cond.NaNvl(_e(a), _e(b))


def if_(cond, a, b):
    return _cond.If(_e(cond), _e(a), _e(b))


def isnull(e):
    return _pred.IsNull(_e(e))


def isnan(e):
    return _pred.IsNaN(_e(e))


def is_in(e, *items):
    return _pred.In(_e(e), [_e(i) for i in items])


# math
def sqrt(e):
    return _math.Sqrt(_e(e))


def exp(e):
    return _math.Exp(_e(e))


def log(e):
    return _math.Log(_e(e))


def log10(e):
    return _math.Log10(_e(e))


def log2(e):
    return _math.Log2(_e(e))


def pow(a, b):  # noqa: A001
    return _math.Pow(_e(a), _e(b))


def abs(e):  # noqa: A001
    from spark_rapids_tpu.ops.arithmetic import Abs
    return Abs(_e(e))


def ceil(e):
    return _math.Ceil(_e(e))


def floor(e):
    return _math.Floor(_e(e))


def round(e, scale=0):  # noqa: A001
    return _math.Round(_e(e), lit(scale))


def bround(e, scale=0):
    return _math.BRound(_e(e), lit(scale))


def signum(e):
    return _math.Signum(_e(e))


def shiftleft(e, n):
    return _math.ShiftLeft(_e(e), _e(n))


def shiftright(e, n):
    return _math.ShiftRight(_e(e), _e(n))


# window functions: thin delegates to the single implementations in
# ops/window.py (reference: window/ package exprs)
def row_number():
    from spark_rapids_tpu.ops import window as _w
    return _w.row_number()


def rank():
    from spark_rapids_tpu.ops import window as _w
    return _w.rank()


def dense_rank():
    from spark_rapids_tpu.ops import window as _w
    return _w.dense_rank()


def lag(e, offset: int = 1, default=None):
    from spark_rapids_tpu.ops import window as _w
    return _w.lag(_e(e), offset, default)


def lead(e, offset: int = 1, default=None):
    from spark_rapids_tpu.ops import window as _w
    return _w.lead(_e(e), offset, default)
