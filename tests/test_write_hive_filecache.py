"""WriteFiles commit protocol, Hive text scan, FileCache
(reference analogs: GpuDataWritingCommandExec, GpuHiveText, FileCache)."""

import os

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table

from tests.data_gen import IntGen, StringGen, gen_table


def _df(sess, n=200, seed=4):
    return from_host_table(
        gen_table({"k": StringGen(cardinality=4, nullable=False),
                   "v": IntGen(nullable=False)}, n, seed), sess)


def test_write_parquet_commit_protocol(session, tmp_path):
    out = str(tmp_path / "t")
    stats = _df(session).filter(col("v") > lit(0)).write_parquet(out)
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    assert not any(d.startswith("_temporary") for d in os.listdir(out))
    row = stats.to_pydict()
    assert row["numFiles"][0] >= 1 and row["numBytes"][0] > 0
    back = session.read_parquet(out + "/part-00000.parquet").count()
    assert back == row["numRows"][0]


def test_write_partitioned_commit(session, tmp_path):
    out = str(tmp_path / "p")
    stats = _df(session).write_parquet(out, partition_by=["k"])
    assert os.path.exists(os.path.join(out, "_SUCCESS"))
    parts = [d for d in os.listdir(out) if d.startswith("k=")]
    assert len(parts) >= 2
    assert stats.to_pydict()["numRows"][0] == 200


def test_hive_text_roundtrip(session, tmp_path):
    out = str(tmp_path / "h")
    _df(session).write_hive_text(out)
    schema = [("k", T.STRING), ("v", T.INT)]
    files = [os.path.join(out, f) for f in os.listdir(out)
             if f.endswith(".txt")]
    back = session.read_hive_text(*files, schema=schema)
    a = sorted(back.collect())
    b = sorted(_df(session).collect())
    assert a == b


def test_hive_text_null_marker(session, tmp_path):
    p = str(tmp_path / "n.txt")
    with open(p, "w") as f:
        f.write("a\x015\n\\N\x017\nb\x01\\N\n")
    df = session.read_hive_text(p, schema=[("s", T.STRING), ("i", T.INT)])
    assert df.collect() == [("a", 5), (None, 7), ("b", None)]


def test_filecache_hits(tmp_path):
    from spark_rapids_tpu.io.filecache import FILE_CACHE
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession({"spark.rapids.filecache.enabled": "true"})
    out = str(tmp_path / "c")
    _df(s).write_parquet(out)
    f = os.path.join(out, "part-00000.parquet")
    FILE_CACHE.clear()
    h0, m0 = FILE_CACHE.hits, FILE_CACHE.misses
    s.read_parquet(f).count()
    s.read_parquet(f).count()
    assert FILE_CACHE.misses == m0 + 1
    assert FILE_CACHE.hits >= h0 + 1


def test_filecache_disabled_by_default(session, tmp_path):
    from spark_rapids_tpu.io.filecache import FILE_CACHE
    out = str(tmp_path / "d")
    _df(session).write_parquet(out)
    FILE_CACHE.clear()
    m0 = FILE_CACHE.misses
    session.read_parquet(os.path.join(out, "part-00000.parquet")).count()
    assert FILE_CACHE.misses == m0  # cache never consulted


# -- hive serde breadth (VERDICT r4 weak #7) ---------------------------------

def test_hive_text_boolean_and_custom_serde(session, tmp_path):
    """Hive renders booleans lowercase; field.delim /
    serialization.null.format properties honor custom values."""
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.io.hive_text import write_hive_text

    t = HostTable.from_pydict(
        {"b": [True, False, None], "n": [1, None, 3]},
        dtypes={"b": T.BOOLEAN, "n": T.LONG})
    files = write_hive_text(t, str(tmp_path / "h"), delimiter="|",
                            null_value="NULLY")
    raw = open(files[0]).read().splitlines()
    assert raw == ["true|1", "false|NULLY", "NULLY|3"]
    got = session.read_hive_text(
        str(tmp_path / "h"), schema=[("b", T.BOOLEAN), ("n", T.LONG)],
        delimiter="|", null_value="NULLY").collect()
    assert sorted(got, key=repr) == sorted(
        [(True, 1), (False, None), (None, 3)], key=repr)


def test_hive_text_escape_delim_roundtrip(session, tmp_path):
    """escape.delim: delimiters inside string values escape on write and
    unescape on read instead of splitting the row."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.io.hive_text import write_hive_text

    # escape.delim is an arbitrary byte in Hive; backslash specifically
    # conflicts with the \N null marker in the parser, so use another
    t = HostTable.from_pydict({"s": ["a|b", "nl\nin", None, "t~e"],
                               "x": [1, 2, 3, 4]},
                              dtypes={"s": T.STRING, "x": T.LONG})
    write_hive_text(t, str(tmp_path / "e"), delimiter="|", escape="~")
    raw = sorted(open(f).read() for f in
                 __import__("glob").glob(str(tmp_path / "e" / "*.txt")))
    assert "a~|b|1" in raw[0]  # delimiter escaped on disk
    got = session.read_hive_text(
        str(tmp_path / "e"), schema=[("s", T.STRING), ("x", T.LONG)],
        delimiter="|", escape="~").collect()
    assert sorted(got, key=repr) == sorted(
        [("a|b", 1), ("nl\nin", 2), (None, 3), ("t~e", 4)], key=repr)


def test_hive_text_escape_applies_to_rendered_numerics(session, tmp_path):
    """A LONG of -5 under delimiter='-' must escape its rendered text,
    not split the row (review fix)."""
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.io.hive_text import write_hive_text

    t = HostTable.from_pydict({"a": [-5, 7], "b": [1, 2]},
                              dtypes={"a": T.LONG, "b": T.LONG})
    write_hive_text(t, str(tmp_path / "neg"), delimiter="-", escape="~")
    got = session.read_hive_text(
        str(tmp_path / "neg"), schema=[("a", T.LONG), ("b", T.LONG)],
        delimiter="-", escape="~").collect()
    assert sorted(got) == [(-5, 1), (7, 2)]


def test_hive_text_partitioned_table(session, cpu_session, tmp_path):
    """Partitioned hive-text table (key=value dirs): partition columns
    recover through the shared scan machinery."""
    import numpy as np
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.io.hive_text import write_hive_text

    t = HostTable.from_pydict(
        {"v": list(range(6)),
         "p": ["x", "y", "x", "y", "x", "y"]},
        dtypes={"v": T.LONG, "p": T.STRING})
    write_hive_text(t, str(tmp_path / "pt"), partition_by=["p"])
    import glob
    assert glob.glob(str(tmp_path / "pt" / "p=x" / "*.txt"))

    def q(s):
        return s.read_hive_text(str(tmp_path / "pt"),
                                schema=[("v", T.LONG)]).sort("v")

    got = q(session).collect()
    want = q(cpu_session).collect()
    assert got == want
    by_v = {r[0]: r[1] for r in got}
    assert by_v[0] == "x" and by_v[1] == "y" and len(by_v) == 6
