"""Struct/map expressions + higher-order array functions.

Reference: complexTypeCreator.scala (GpuCreateNamedStruct/GpuCreateMap),
complexTypeExtractors (GpuGetStructField/GpuGetMapValue),
collectionOperations.scala (map_keys/map_values/map_entries/map_concat),
higherOrderFunctions.scala (GpuArrayTransform/Exists/Filter +
GpuLambdaFunction/GpuNamedLambdaVariable binding) — SURVEY.md §2.3 #26,
VERDICT r3 missing #1/#2.

TPU-first lambda evaluation: a lambda body is an ordinary expression tree
evaluated over the ELEMENT space — the array's flat (elems, evalid)
buffers — with lambda variables bound to the element streams and any
outer-row references gathered per element by row id. The body therefore
compiles into the same fused XLA program as everything else; there is no
per-row interpretation (the reference reaches the same shape by evaluating
the bound lambda over the child LIST column's child column).

The body is REBOUND at resolve time: NamedLambdaVariable -> element-ctx
ordinal 0..k-1, outer BoundReference(i) -> k + dense index. Element-space
liveness = (slot < total elements) & parent row live."""

from __future__ import annotations

from types import SimpleNamespace
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import HostColumn, HostTable, bucket_for
from spark_rapids_tpu.columnar.nested import (
    MapData,
    StructData,
    fixed_np_dtype,
    map_device_supported,
    struct_device_supported,
)
from spark_rapids_tpu.errors import ColumnarProcessingError, UnsupportedOnTpu
from spark_rapids_tpu.ops.collections import _elem_rids, is_fixed_array
from spark_rapids_tpu.ops.common import UnaryExpression
from spark_rapids_tpu.ops.expr import (
    BoundReference,
    DevVal,
    EvalCtx,
    Expression,
    NodePrep,
    PrepCtx,
    _prep_trace_key,
    _walk_eval,
    _walk_prep,
    output_name,
)


# ---------------------------------------------------------------------------
# structs
# ---------------------------------------------------------------------------

class CreateNamedStruct(Expression):
    """named_struct(n1, e1, n2, e2, ...) — bundles existing columns; zero
    data movement on device."""

    def __init__(self, names: Sequence[str], exprs: Sequence[Expression]):
        self.names = tuple(names)
        self.children = tuple(exprs)

    @property
    def data_type(self):
        return T.StructType([
            T.StructField(n, e.data_type)
            for n, e in zip(self.names, self.children)])

    @property
    def nullable(self):
        return False

    def key(self):
        return ("namedstruct", self.names,
                tuple(c.key() for c in self.children))

    def with_children(self, children):
        return CreateNamedStruct(self.names, children)

    @property
    def device_supported(self):
        return struct_device_supported(self.data_type)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        kids = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = tuple(
                (k.data[i].item() if hasattr(k.data[i], "item")
                 else k.data[i]) if k.validity[i] else None
                for k in kids)
        return HostColumn(self.data_type, out, np.ones(n, dtype=np.bool_))

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        sd = StructData(tuple((cv.data, cv.validity) for cv in child_vals))
        return DevVal(sd, ctx.row_mask())


class GetStructField(Expression):
    def __init__(self, child: Expression, name: str):
        self.children = (child,)
        self.field_name = name

    def _field(self):
        st: T.StructType = self.children[0].data_type
        for i, f in enumerate(st.fields):
            if f.name == self.field_name:
                return i, f
        raise ColumnarProcessingError(
            f"no field {self.field_name!r} in {st.simple_string()}")

    @property
    def data_type(self):
        return self._field()[1].data_type

    def key(self):
        return ("getfield", self.field_name, self.children[0].key())

    def with_children(self, children):
        return GetStructField(children[0], self.field_name)

    @property
    def device_supported(self):
        return struct_device_supported(self.children[0].data_type)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        c = self.children[0].eval_cpu(table)
        fi, f = self._field()
        n = len(c)
        npdt = fixed_np_dtype(f.data_type)
        data = np.zeros(n, dtype=npdt if npdt is not None else object)
        validity = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            if c.validity[i]:
                row = c.data[i]
                v = row.get(self.field_name) if isinstance(row, dict) \
                    else row[fi]
                if v is not None:
                    data[i] = v
                    validity[i] = True
        return HostColumn(f.data_type, data, validity)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        (c,) = child_vals
        fi, _ = self._field()
        d, v = c.data.fields[fi]
        return DevVal(d, v & c.validity)


# ---------------------------------------------------------------------------
# maps
# ---------------------------------------------------------------------------

class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) — fixed entry count per row. Null keys are
    invalid in Spark (runtime error): the CPU path raises at evaluation;
    the device kernel cannot raise per-row, so a null key marks kvalid
    False and the error surfaces at collect (columnar/nested.map_to_host)
    — never silent wrong data."""

    def __init__(self, *children: Expression):
        if len(children) % 2 != 0 or not children:
            raise ColumnarProcessingError("map() needs key/value pairs")
        self.children = tuple(children)

    @property
    def data_type(self):
        return T.MapType(key_type=self.children[0].data_type,
                         value_type=self.children[1].data_type)

    @property
    def nullable(self):
        return False

    def key(self):
        return ("createmap", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return CreateMap(*children)

    @property
    def device_supported(self):
        return map_device_supported(self.data_type)

    def eval_cpu(self, table: HostTable) -> HostColumn:
        kids = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=object)
        for i in range(n):
            m = {}
            for j in range(0, len(kids), 2):
                kc, vc = kids[j], kids[j + 1]
                if not kc.validity[i]:
                    raise ColumnarProcessingError(
                        "Cannot use null as map key")
                k = kc.data[i].item() if hasattr(kc.data[i], "item") \
                    else kc.data[i]
                m[k] = (vc.data[i].item() if hasattr(vc.data[i], "item")
                        else vc.data[i]) if vc.validity[i] else None
            out[i] = m
        return HostColumn(self.data_type, out, np.ones(n, dtype=np.bool_))

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        cap = ctx.capacity
        k = len(child_vals) // 2
        ecap = bucket_for(max(cap * k, 1))
        kd = jnp.stack([child_vals[2 * j].data for j in range(k)],
                       axis=1).reshape(cap * k)
        kv = jnp.stack([child_vals[2 * j].validity for j in range(k)],
                       axis=1).reshape(cap * k)
        vd = jnp.stack([child_vals[2 * j + 1].data for j in range(k)],
                       axis=1).reshape(cap * k)
        vv = jnp.stack([child_vals[2 * j + 1].validity for j in range(k)],
                       axis=1).reshape(cap * k)
        pad = ecap - cap * k
        if pad:
            kd = jnp.concatenate([kd, jnp.zeros(pad, kd.dtype)])
            kv = jnp.concatenate([kv, jnp.zeros(pad, jnp.bool_)])
            vd = jnp.concatenate([vd, jnp.zeros(pad, vd.dtype)])
            vv = jnp.concatenate([vv, jnp.zeros(pad, jnp.bool_)])
        off = jnp.arange(cap + 1, dtype=jnp.int32) * k
        md = MapData(off, kd, kv, vd, vv)
        return DevVal(md, ctx.row_mask())


class _MapUnary(UnaryExpression):
    @property
    def device_supported(self):
        return map_device_supported(self.children[0].data_type)


class MapKeys(_MapUnary):
    @property
    def data_type(self):
        return T.ArrayType(self.children[0].data_type.key_type)

    def key(self):
        return ("mapkeys", self.children[0].key())

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        out = np.empty(len(c), dtype=object)
        for i in range(len(c)):
            if c.validity[i]:
                out[i] = list(c.data[i].keys())
        return HostColumn(self.data_type, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        (c,) = child_vals
        md: MapData = c.data
        return DevVal((md.offsets, md.kdata, md.kvalid), c.validity)


class MapValues(_MapUnary):
    @property
    def data_type(self):
        return T.ArrayType(self.children[0].data_type.value_type)

    def key(self):
        return ("mapvalues", self.children[0].key())

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        out = np.empty(len(c), dtype=object)
        for i in range(len(c)):
            if c.validity[i]:
                out[i] = list(c.data[i].values())
        return HostColumn(self.data_type, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        (c,) = child_vals
        md: MapData = c.data
        return DevVal((md.offsets, md.vdata, md.vvalid), c.validity)


class MapEntries(_MapUnary):
    """map_entries(m) -> array<struct<key,value>>. Device arrays hold
    fixed-width elements only, so this one is CPU-path (tagged)."""

    device_supported = False

    @property
    def data_type(self):
        mt = self.children[0].data_type
        return T.ArrayType(T.StructType([
            T.StructField("key", mt.key_type, False),
            T.StructField("value", mt.value_type)]))

    def key(self):
        return ("mapentries", self.children[0].key())

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        out = np.empty(len(c), dtype=object)
        for i in range(len(c)):
            if c.validity[i]:
                out[i] = [(k, v) for k, v in c.data[i].items()]
        return HostColumn(self.data_type, out, c.validity.copy())


class GetMapValue(Expression):
    """m[key] — per-row lookup; missing key -> null."""

    def __init__(self, child: Expression, key_expr: Expression):
        self.children = (child, key_expr)

    @property
    def data_type(self):
        return self.children[0].data_type.value_type

    def key(self):
        return ("getmapvalue", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return GetMapValue(children[0], children[1])

    @property
    def device_supported(self):
        return map_device_supported(self.children[0].data_type)

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        k = self.children[1].eval_cpu(table)
        vt = self.data_type
        npdt = fixed_np_dtype(vt)
        n = len(c)
        data = np.zeros(n, dtype=npdt if npdt is not None else object)
        validity = np.zeros(n, dtype=np.bool_)
        for i in range(n):
            if c.validity[i] and k.validity[i]:
                kk = k.data[i].item() if hasattr(k.data[i], "item") \
                    else k.data[i]
                if kk in c.data[i] and c.data[i][kk] is not None:
                    data[i] = c.data[i][kk]
                    validity[i] = True
        return HostColumn(vt, data, validity)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        c, k = child_vals
        md: MapData = c.data
        cap = ctx.capacity
        ecap = int(md.kdata.shape[0])
        rid = _elem_rids(md.offsets, ecap, cap)
        safe_rid = jnp.clip(rid, 0, cap - 1)
        hit = (rid < cap) & md.kvalid & k.validity[safe_rid] & \
            (md.kdata == k.data[safe_rid])
        # last entry wins (Spark map semantics keep last duplicate)
        pos = jnp.where(hit, jnp.arange(ecap, dtype=jnp.int32), -1)
        best = jnp.full(cap + 1, -1, jnp.int32).at[
            jnp.where(rid < cap, rid, cap)].max(pos, mode="drop")[:cap]
        found = best >= 0
        safe = jnp.clip(best, 0, ecap - 1)
        data = md.vdata[safe]
        validity = found & md.vvalid[safe] & c.validity & k.validity
        return DevVal(jnp.where(validity, data, jnp.zeros_like(data)),
                      validity)


class MapConcat(Expression):
    """map_concat(m1, m2, ...) — LAST_WIN dedup across inputs (Spark's
    mapKeyDedupPolicy=LAST_WIN; the EXCEPTION default cannot raise per-row
    on device, matching the reference's policy-gated support)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def data_type(self):
        return self.children[0].data_type

    def key(self):
        return ("mapconcat", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return MapConcat(*children)

    @property
    def device_supported(self):
        return all(map_device_supported(c.data_type)
                   for c in self.children)

    def eval_cpu(self, table):
        kids = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=object)
        validity = np.ones(n, dtype=np.bool_)
        for i in range(n):
            if any(not k.validity[i] for k in kids):
                validity[i] = False
                continue
            m = {}
            for k in kids:
                m.update(k.data[i])
            out[i] = m
        return HostColumn(self.data_type, out, validity)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        cap = ctx.capacity
        validity = ctx.row_mask()
        for cv in child_vals:
            validity = validity & cv.validity
        # concatenate entry streams, tagging each element with (row, order)
        rids, kds, kvs, vds, vvs, orders = [], [], [], [], [], []
        base = 0
        for ci, cv in enumerate(child_vals):
            md: MapData = cv.data
            ecap = int(md.kdata.shape[0])
            rid = _elem_rids(md.offsets, ecap, cap)
            live = (rid < cap) & md.kvalid & cv.validity[
                jnp.clip(rid, 0, cap - 1)]
            rids.append(jnp.where(live, rid, cap))
            kds.append(md.kdata)
            kvs.append(live)
            vds.append(md.vdata)
            vvs.append(md.vvalid)
            orders.append(jnp.arange(ecap, dtype=jnp.int32) + base)
            base += ecap
        rid = jnp.concatenate(rids)
        kd = jnp.concatenate(kds)
        kv = jnp.concatenate(kvs)
        vd = jnp.concatenate(vds)
        vv = jnp.concatenate(vvs)
        order = jnp.concatenate(orders)
        tot = int(rid.shape[0])
        ecap_out = bucket_for(max(tot, 1))
        from spark_rapids_tpu.ops.ordering import comparable_operands
        kops = comparable_operands(jnp.where(kv, kd, jnp.zeros_like(kd)))
        payload = jnp.arange(tot, dtype=jnp.int32)
        res = jax.lax.sort([rid] + kops + [order, payload],
                           num_keys=1 + len(kops) + 1)
        s_rid = res[0]
        perm = res[-1]
        # last occurrence of each (row, key) wins: keep where the NEXT
        # sorted entry differs in (row, key)
        nxt_same = (s_rid == jnp.concatenate(
            [s_rid[1:], jnp.full(1, cap + 1, s_rid.dtype)]))
        for o in res[1:1 + len(kops)]:
            nxt = jnp.concatenate([o[1:], jnp.zeros(1, o.dtype) - 1])
            nxt_same = nxt_same & (o == nxt)
        keep = (s_rid < cap) & ~nxt_same
        new_rid = jnp.where(keep, s_rid, cap)
        counts = jax.ops.segment_sum(keep.astype(jnp.int32), new_rid,
                                     num_segments=cap + 1)[:cap]
        off = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               jnp.cumsum(counts).astype(jnp.int32)])
        cpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        tgt = jnp.where(keep, cpos, ecap_out)
        from spark_rapids_tpu.ops.scatter32 import scatter_pair
        okd, okv = scatter_pair(ecap_out, tgt, kd[perm], kv[perm])
        ovd, ovv = scatter_pair(ecap_out, tgt, vd[perm], vv[perm])
        return DevVal(MapData(off, okd, okv, ovd, ovv), validity)


# ---------------------------------------------------------------------------
# lambdas
# ---------------------------------------------------------------------------

class NamedLambdaVariable(Expression):
    """Placeholder bound by resolve() of the enclosing HOF."""

    def __init__(self, name: str, dtype: Optional[T.DataType] = None):
        self.var_name = name
        self._dtype = dtype

    @property
    def data_type(self):
        if self._dtype is None:
            raise ColumnarProcessingError(
                f"unbound lambda variable {self.var_name}")
        return self._dtype

    def key(self):
        return ("lambdavar", self.var_name, str(self._dtype))

    def with_children(self, children):
        return self

    def bind(self, schema):
        return self  # bound by the HOF, not by row schema

    def eval_cpu(self, table):
        raise ColumnarProcessingError(
            f"lambda variable {self.var_name} evaluated outside a lambda")

    eval_dev = eval_cpu


class LambdaFunction(Expression):
    """x -> body or (x, y) -> body. Never evaluated directly; the HOF
    rebinds and evaluates the body in element space."""

    def __init__(self, body: Expression, var_names: Sequence[str]):
        self.children = (body,)
        self.var_names = tuple(var_names)

    @property
    def body(self):
        return self.children[0]

    @property
    def data_type(self):
        return self.body.data_type

    def key(self):
        return ("lambda", self.var_names, self.body.key())

    def with_children(self, children):
        return LambdaFunction(children[0], self.var_names)

    def bind(self, schema):
        # binding is driven by the enclosing HOF: lambda vars must be
        # TYPED before the body binds (type coercion consults data_type)
        return self

    def eval_cpu(self, table):
        raise ColumnarProcessingError("LambdaFunction evaluated directly")

    eval_dev = eval_cpu


def _tree_device_supported(e: Expression) -> bool:
    """check_expr never sees the rebound lambda body (it is not a child),
    so the HOF vouches for the WHOLE body tree itself."""
    if not getattr(e, "device_supported", True):
        return False
    return all(_tree_device_supported(c) for c in e.children)


def _substitute_vars(e: Expression, mapping) -> Expression:
    if isinstance(e, NamedLambdaVariable):
        got = mapping.get(e.var_name)
        return got if got is not None else e
    if not e.children:
        return e
    return e.with_children([_substitute_vars(c, mapping)
                            for c in e.children])


def _collect_outer_refs(e: Expression, acc: set) -> None:
    if isinstance(e, BoundReference):
        acc.add(e.ordinal)
    for c in e.children:
        _collect_outer_refs(c, acc)


class _HigherOrder(Expression):
    """Shared machinery. After bind(), ``children`` = (array/map expr,
    *outer_exprs): outer row-space subexpressions the lambda body
    references, evaluated by the generic walkers in row space and gathered
    per element — this keeps the fusion substitution pass (execs/fuse.py)
    and every other generic child rewrite sound. The body lives REBOUND in
    ``self._rebound``: lambda var i -> element-ctx ordinal i, outer expr j
    -> ordinal n_vars + j."""

    def __init__(self, child: Expression, fn: LambdaFunction,
                 _rebound=None, _outer_children=()):
        self.children = (child,) + tuple(_outer_children)
        self.fn = fn
        self._rebound = _rebound  # body with element-ctx ordinals

    def _var_types(self) -> List[T.DataType]:
        raise NotImplementedError

    def key(self):
        return (type(self).__name__.lower(),
                tuple(c.key() for c in self.children),
                self.fn.key() if self._rebound is None
                else self._rebound.key())

    def with_children(self, children):
        return type(self)(children[0], self.fn, self._rebound,
                          tuple(children[1:]))

    def bind(self, schema):
        child = self.children[0].bind(schema)
        fn = self.fn
        out = type(self)(child, fn)
        # type the lambda vars FIRST (binding coerces via data_type), then
        # bind row-space refs, then rebind into element space; outer row
        # refs become explicit CHILDREN of this node
        vts = out._var_types()
        mapping = {name: NamedLambdaVariable(name, vt)
                   for name, vt in zip(fn.var_names, vts)}
        typed = _substitute_vars(fn.body, mapping).bind(schema)
        outer: set = set()
        _collect_outer_refs(typed, outer)
        outer_sorted = sorted(outer)
        # element ctx always carries len(vts) variable columns (map HOFs
        # supply both streams even to a 1-arg lambda)
        k = len(vts)
        remap = {o: k + i for i, o in enumerate(outer_sorted)}

        def rebind(e):
            if isinstance(e, NamedLambdaVariable):
                idx = fn.var_names.index(e.var_name)
                return BoundReference(idx, vts[idx], name_hint=e.var_name)
            if isinstance(e, BoundReference):
                return BoundReference(remap[e.ordinal], e.data_type,
                                      e.nullable, name_hint=e.name_hint)
            if not e.children:
                return e
            return e.with_children([rebind(c) for c in e.children])

        outer_children = tuple(
            BoundReference(o, schema[o][1], name_hint=schema[o][0])
            for o in outer_sorted)
        return type(self)(child, fn, rebind(typed), outer_children)

    @property
    def device_supported(self):
        dt = self.children[0].data_type
        if isinstance(dt, T.MapType):
            if not map_device_supported(dt):
                return False
        elif not is_fixed_array(dt):
            return False
        if any(fixed_np_dtype(c.data_type) is None
               for c in self.children[1:]):
            return False  # element-space gathers are fixed-width only
        if self._rebound is None:
            return True
        return _tree_device_supported(self._rebound)

    # -- element-space prep/eval shared by all HOFs -------------------------
    def prep(self, pctx: PrepCtx, child_preps):
        vts = self._var_types()
        cols = [SimpleNamespace(dtype=vt, dictionary=None, dict_sorted=True,
                                data=None, validity=None) for vt in vts]
        for c in self.children[1:]:
            cols.append(SimpleNamespace(
                dtype=c.data_type, dictionary=None, dict_sorted=True,
                data=None, validity=None))
        facade = SimpleNamespace(columns=cols,
                                 num_rows=getattr(pctx.table, "num_rows", 0),
                                 capacity=getattr(pctx.table, "capacity", 0))
        sub = PrepCtx.__new__(PrepCtx)
        sub.table = facade
        sub.aux_arrays = pctx.aux_arrays
        sub.aux_intern = pctx.aux_intern
        body_preps: List[NodePrep] = []
        _walk_prep(self._rebound, sub, body_preps)
        p = NodePrep(extra={"body": _prep_trace_key(body_preps)})
        p.body_preps = body_preps
        return p

    def _eval_body(self, ctx: EvalCtx, prep, var_vals: List[DevVal],
                   outer_vals, rid, ecap: int, elem_live):
        """Evaluate the rebound body over element space."""
        cap = ctx.capacity
        safe = jnp.clip(rid, 0, cap - 1)
        cols = list(var_vals)
        for d, v in outer_vals:
            cols.append(DevVal(d[safe], v[safe] & (rid < cap)))
        ectx = EvalCtx(cols, ctx.aux, jnp.asarray(ecap, jnp.int32), ecap,
                       live=elem_live)
        ectx._prep_iter = iter(prep.body_preps)
        return _walk_eval(self._rebound, ectx)

    # -- CPU oracle ---------------------------------------------------------
    def _eval_body_cpu(self, table: HostTable, var_cols: List[HostColumn],
                       rids: np.ndarray) -> HostColumn:
        cols = list(var_cols)
        names = [f"__v{i}" for i in range(len(var_cols))]
        for j, c in enumerate(self.children[1:]):
            src = c.eval_cpu(table)
            cols.append(HostColumn(src.dtype, src.data[rids],
                                   src.validity[rids]))
            names.append(f"__o{j}")
        elem_table = HostTable(names, cols)
        return self._rebound.eval_cpu(elem_table)


class ArrayTransform(_HigherOrder):
    """transform(arr, x -> f(x)) (optionally (x, i) -> ...)."""

    def _var_types(self):
        et = self.children[0].data_type.element_type
        return [et, T.INT][:len(self.fn.var_names)]

    @property
    def data_type(self):
        return T.ArrayType(self._rebound.data_type
                           if self._rebound is not None
                           else self.fn.body.data_type)

    @property
    def device_supported(self):
        return (super().device_supported
                and fixed_np_dtype(self.data_type.element_type) is not None)

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        n = len(c)
        rids, elems, evalid = _flatten_cpu(c)
        vts = self._var_types()
        var_cols = [HostColumn(vts[0], elems, evalid)]
        if len(vts) > 1:
            var_cols.append(HostColumn(T.INT, _positions_cpu(c), np.ones(
                len(elems), dtype=np.bool_)))
        body = self._eval_body_cpu(table, var_cols, rids)
        out = np.empty(n, dtype=object)
        pos = 0
        for i in range(n):
            if c.validity[i]:
                ln = len(c.data[i])
                out[i] = [
                    (body.data[pos + j].item()
                     if hasattr(body.data[pos + j], "item")
                     else body.data[pos + j])
                    if body.validity[pos + j] else None
                    for j in range(ln)]
                pos += ln
        return HostColumn(self.data_type, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        c = child_vals[0]
        off, ed, ev = c.data
        ecap = int(ed.shape[0])
        cap = ctx.capacity
        rid = _elem_rids(off, ecap, cap)
        elem_live = rid < cap
        var_vals = [DevVal(ed, ev)]
        if len(self.fn.var_names) > 1:
            pos = jnp.arange(ecap, dtype=jnp.int32) - off[
                jnp.clip(rid, 0, cap - 1)]
            var_vals.append(DevVal(pos.astype(jnp.int32), elem_live))
        body = self._eval_body(ctx, prep, var_vals, child_vals[1:], rid,
                               ecap, elem_live)
        return DevVal((off, body.data, body.validity & elem_live),
                      c.validity)


class ArrayFilter(_HigherOrder):
    """filter(arr, x -> pred)."""

    def _var_types(self):
        et = self.children[0].data_type.element_type
        return [et, T.INT][:len(self.fn.var_names)]

    @property
    def data_type(self):
        return self.children[0].data_type

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        n = len(c)
        rids, elems, evalid = _flatten_cpu(c)
        vts = self._var_types()
        var_cols = [HostColumn(vts[0], elems, evalid)]
        if len(vts) > 1:
            var_cols.append(HostColumn(T.INT, _positions_cpu(c), np.ones(
                len(elems), dtype=np.bool_)))
        body = self._eval_body_cpu(table, var_cols, rids)
        out = np.empty(n, dtype=object)
        pos = 0
        for i in range(n):
            if c.validity[i]:
                ln = len(c.data[i])
                out[i] = [c.data[i][j] for j in range(ln)
                          if body.validity[pos + j]
                          and bool(body.data[pos + j])]
                pos += ln
        return HostColumn(self.data_type, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        c = child_vals[0]
        off, ed, ev = c.data
        ecap = int(ed.shape[0])
        cap = ctx.capacity
        rid = _elem_rids(off, ecap, cap)
        elem_live = rid < cap
        var_vals = [DevVal(ed, ev)]
        if len(self.fn.var_names) > 1:
            pos = jnp.arange(ecap, dtype=jnp.int32) - off[
                jnp.clip(rid, 0, cap - 1)]
            var_vals.append(DevVal(pos.astype(jnp.int32), elem_live))
        body = self._eval_body(ctx, prep, var_vals, child_vals[1:], rid,
                               ecap, elem_live)
        keep = body.data & body.validity & elem_live
        counts = jax.ops.segment_sum(
            keep.astype(jnp.int32), jnp.where(elem_live, rid, cap),
            num_segments=cap + 1)[:cap]
        noff = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                jnp.cumsum(counts).astype(jnp.int32)])
        cpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        tgt = jnp.where(keep, cpos, ecap)
        from spark_rapids_tpu.ops.scatter32 import scatter_pair
        ned, nev = scatter_pair(ecap, tgt, ed, ev)
        return DevVal((noff, ned, nev), c.validity)


class _ArrayPredicate(_HigherOrder):
    """exists / forall — Spark three-valued logic."""

    exists = True

    def _var_types(self):
        return [self.children[0].data_type.element_type]

    @property
    def data_type(self):
        return T.BOOLEAN

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        n = len(c)
        rids, elems, evalid = _flatten_cpu(c)
        var_cols = [HostColumn(self._var_types()[0], elems, evalid)]
        body = self._eval_body_cpu(table, var_cols, rids)
        data = np.zeros(n, dtype=np.bool_)
        validity = np.zeros(n, dtype=np.bool_)
        pos = 0
        for i in range(n):
            if not c.validity[i]:
                continue
            ln = len(c.data[i])
            vals = [bool(body.data[pos + j]) if body.validity[pos + j]
                    else None for j in range(ln)]
            pos += ln
            hit = any(v is (True if self.exists else False) for v in vals)
            has_null = any(v is None for v in vals)
            if self.exists:
                data[i], validity[i] = (True, True) if hit else \
                    (False, not has_null)
            else:
                data[i], validity[i] = (False, True) if hit else \
                    (True, not has_null)
        return HostColumn(T.BOOLEAN, data, validity)

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        c = child_vals[0]
        off, ed, ev = c.data
        ecap = int(ed.shape[0])
        cap = ctx.capacity
        rid = _elem_rids(off, ecap, cap)
        elem_live = rid < cap
        body = self._eval_body(ctx, prep, [DevVal(ed, ev)],
                               child_vals[1:], rid, ecap, elem_live)
        seg = jnp.where(elem_live, rid, cap)
        want = body.data if self.exists else ~body.data
        hit = jax.ops.segment_max(
            (want & body.validity & elem_live).astype(jnp.int32), seg,
            num_segments=cap + 1)[:cap] > 0
        nulls = jax.ops.segment_max(
            (~body.validity & elem_live).astype(jnp.int32), seg,
            num_segments=cap + 1)[:cap] > 0
        if self.exists:
            data = hit
            validity = (hit | ~nulls) & c.validity
        else:
            data = ~hit
            validity = (hit | ~nulls) & c.validity
        return DevVal(data & validity, validity)


class ArrayExists(_ArrayPredicate):
    exists = True


class ArrayForAll(_ArrayPredicate):
    exists = False


class _MapLambda(_HigherOrder):
    """Shared (k, v) lambda machinery for map HOFs."""

    def _var_types(self):
        mt = self.children[0].data_type
        return [mt.key_type, mt.value_type]

    def _map_eval(self, ctx, child_vals, prep):
        c = child_vals[0]
        md: MapData = c.data
        ecap = int(md.kdata.shape[0])
        cap = ctx.capacity
        rid = _elem_rids(md.offsets, ecap, cap)
        elem_live = rid < cap
        body = self._eval_body(
            ctx, prep, [DevVal(md.kdata, md.kvalid),
                        DevVal(md.vdata, md.vvalid)],
            child_vals[1:], rid, ecap, elem_live)
        return md, rid, elem_live, body, ecap, cap

    def _flatten_map_cpu(self, c):
        rids, keys, kvalid, vals, vvalid = [], [], [], [], []
        for i in range(len(c)):
            if c.validity[i]:
                for k, v in c.data[i].items():
                    rids.append(i)
                    keys.append(k)
                    kvalid.append(True)
                    vals.append(v if v is not None else 0)
                    vvalid.append(v is not None)
        mt = self.children[0].data_type
        return (np.asarray(rids, dtype=np.int64),
                HostColumn(mt.key_type,
                           np.asarray(keys, dtype=fixed_np_dtype(
                               mt.key_type) or object),
                           np.asarray(kvalid, dtype=np.bool_)),
                HostColumn(mt.value_type,
                           np.asarray(vals, dtype=fixed_np_dtype(
                               mt.value_type) or object),
                           np.asarray(vvalid, dtype=np.bool_)))


class MapFilter(_MapLambda):
    """map_filter(m, (k, v) -> pred)."""

    @property
    def data_type(self):
        return self.children[0].data_type

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        rids, kc, vc = self._flatten_map_cpu(c)
        body = self._eval_body_cpu(table, [kc, vc], rids)
        out = np.empty(len(c), dtype=object)
        pos = 0
        for i in range(len(c)):
            if c.validity[i]:
                m = {}
                for k, v in c.data[i].items():
                    if body.validity[pos] and bool(body.data[pos]):
                        m[k] = v
                    pos += 1
                out[i] = m
        return HostColumn(self.data_type, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        md, rid, elem_live, body, ecap, cap = self._map_eval(
            ctx, child_vals, prep)
        keep = body.data & body.validity & elem_live & md.kvalid
        counts = jax.ops.segment_sum(
            keep.astype(jnp.int32), jnp.where(elem_live, rid, cap),
            num_segments=cap + 1)[:cap]
        noff = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                jnp.cumsum(counts).astype(jnp.int32)])
        cpos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        tgt = jnp.where(keep, cpos, ecap)
        from spark_rapids_tpu.ops.scatter32 import scatter_pair
        nkd, nkv = scatter_pair(ecap, tgt, md.kdata, md.kvalid)
        nvd, nvv = scatter_pair(ecap, tgt, md.vdata, md.vvalid)
        return DevVal(MapData(noff, nkd, nkv, nvd, nvv),
                      child_vals[0].validity)


class TransformValues(_MapLambda):
    """transform_values(m, (k, v) -> f)."""

    @property
    def data_type(self):
        mt = self.children[0].data_type
        vt = self._rebound.data_type if self._rebound is not None \
            else self.fn.body.data_type
        return T.MapType(key_type=mt.key_type, value_type=vt)

    @property
    def device_supported(self):
        return (super().device_supported
                and fixed_np_dtype(self.data_type.value_type) is not None)

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        rids, kc, vc = self._flatten_map_cpu(c)
        body = self._eval_body_cpu(table, [kc, vc], rids)
        out = np.empty(len(c), dtype=object)
        pos = 0
        for i in range(len(c)):
            if c.validity[i]:
                m = {}
                for k in c.data[i]:
                    m[k] = (body.data[pos].item()
                            if hasattr(body.data[pos], "item")
                            else body.data[pos]) \
                        if body.validity[pos] else None
                    pos += 1
                out[i] = m
        return HostColumn(self.data_type, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        md, rid, elem_live, body, ecap, cap = self._map_eval(
            ctx, child_vals, prep)
        return DevVal(MapData(md.offsets, md.kdata, md.kvalid,
                              body.data, body.validity & elem_live),
                      child_vals[0].validity)


class TransformKeys(_MapLambda):
    """transform_keys(m, (k, v) -> f). Per Spark, a transform producing a
    null key raises; duplicate new keys follow the dedup policy — the
    device kernel applies LAST_WIN (no per-row raise on device)."""

    @property
    def data_type(self):
        mt = self.children[0].data_type
        kt = self._rebound.data_type if self._rebound is not None \
            else self.fn.body.data_type
        return T.MapType(key_type=kt, value_type=mt.value_type)

    @property
    def device_supported(self):
        return (super().device_supported
                and fixed_np_dtype(self.data_type.key_type) is not None)

    def eval_cpu(self, table):
        c = self.children[0].eval_cpu(table)
        rids, kc, vc = self._flatten_map_cpu(c)
        body = self._eval_body_cpu(table, [kc, vc], rids)
        out = np.empty(len(c), dtype=object)
        pos = 0
        for i in range(len(c)):
            if c.validity[i]:
                m = {}
                for k, v in c.data[i].items():
                    if not body.validity[pos]:
                        raise ColumnarProcessingError(
                            "Cannot use null as map key")
                    nk = body.data[pos].item() \
                        if hasattr(body.data[pos], "item") else body.data[pos]
                    m[nk] = v
                    pos += 1
                out[i] = m
        return HostColumn(self.data_type, out, c.validity.copy())

    def eval_dev(self, ctx, child_vals, prep) -> DevVal:
        md, rid, elem_live, body, ecap, cap = self._map_eval(
            ctx, child_vals, prep)
        return DevVal(MapData(md.offsets, body.data,
                              body.validity & elem_live,
                              md.vdata, md.vvalid),
                      child_vals[0].validity)


class ArraysZip(Expression):
    """arrays_zip(a1, a2, ...) -> array<struct<...>> — CPU path (device
    arrays hold fixed-width elements only; array<struct> is not device-
    representable yet, same carve-out as MapEntries)."""

    device_supported = False

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    @property
    def data_type(self):
        return T.ArrayType(T.StructType([
            T.StructField(str(i), c.data_type.element_type)
            for i, c in enumerate(self.children)]))

    def key(self):
        return ("arrayszip", tuple(c.key() for c in self.children))

    def with_children(self, children):
        return ArraysZip(*children)

    def eval_cpu(self, table):
        kids = [c.eval_cpu(table) for c in self.children]
        n = table.num_rows
        out = np.empty(n, dtype=object)
        validity = np.ones(n, dtype=np.bool_)
        for i in range(n):
            if any(not k.validity[i] for k in kids):
                validity[i] = False
                continue
            ln = max(len(k.data[i]) for k in kids)
            out[i] = [tuple(k.data[i][j] if j < len(k.data[i]) else None
                            for k in kids) for j in range(ln)]
        return HostColumn(self.data_type, out, validity)


# -- cpu flatten helpers -----------------------------------------------------

def _flatten_cpu(c: HostColumn):
    rids, elems, evalid = [], [], []
    edt = fixed_np_dtype(c.dtype.element_type)
    for i in range(len(c)):
        if c.validity[i]:
            for v in c.data[i]:
                rids.append(i)
                elems.append(v if v is not None else 0)
                evalid.append(v is not None)
    return (np.asarray(rids, dtype=np.int64),
            np.asarray(elems, dtype=edt or object),
            np.asarray(evalid, dtype=np.bool_))


def _positions_cpu(c: HostColumn):
    pos = []
    for i in range(len(c)):
        if c.validity[i]:
            pos.extend(range(len(c.data[i])))
    return np.asarray(pos, dtype=np.int32)
