"""AOT warmup: replay an event-log corpus's plan templates before
traffic arrives.

``python -m spark_rapids_tpu.tools warmup --eventlog-dir DIR`` reads
the query event logs a previous serving period wrote
(``spark.rapids.sql.eventLog.*``), reduces them to DISTINCT plans
(full structural fingerprints — plan/fingerprint.py; literal variants
each replay, because numeric literal values trace as XLA constants
and need their own programs), and executes each once over a generated
warehouse, so that:

* every kernel shape the corpus needs is traced/lowered/compiled into
  the process-wide kernel caches (and, on non-CPU backends, the
  PERSISTENT XLA compile cache — the ~1-2 min/shape cold cliff is paid
  here, not on the first user query);
* the plan->executable cache holds each template's converted tree;
* the report says exactly what was compiled vs already warm
  (programsCompiled / programsSkipped, per-query compileMs).

Replay identity comes from the records two ways, most-specific first:

* ``queryTag`` — harness tags are ``<qname>[@tenant][_serial[_cold]]``;
  the qname resolves against the TPC-H corpus builders
  (scale_test.py), which is what ``tools loadtest``/``bench`` traffic
  records;
* ``sqlText`` — replayed through ``session.sql`` over the generated
  tables registered as temp views (arbitrary SQL traffic, as long as
  it binds against the warehouse).

Records matching neither are reported as unmatched, never silently
dropped.
"""

from __future__ import annotations

import re
import time
from typing import Dict, List, Optional

#: harness tag suffixes: q3@tenant1, q3_serial_cold, q3_serial, q3_cold
_TAG_RE = re.compile(r"^(?P<name>[A-Za-z0-9_]+?)"
                     r"(?:_serial)?(?:_cold)?(?:@[\w-]+)?$")


def _corpus_name(tag: Optional[str], known) -> Optional[str]:
    if not tag:
        return None
    m = _TAG_RE.match(tag.split("@")[0])
    if m and m.group("name") in known:
        return m.group("name")
    # tolerate bare q-names with decorations the regex missed
    base = tag.split("@")[0]
    for suffix in ("_serial_cold", "_serial", "_cold"):
        if base.endswith(suffix):
            base = base[: -len(suffix)]
    return base if base in known else None


def run_warmup(eventlog_dir: str, sf: float = 0.05, seed: int = 0,
               use_sql: bool = False, tables: Optional[Dict] = None,
               session=None) -> dict:
    """Replay the event-log corpus under ``eventlog_dir``; returns the
    JSON-ready report. ``tables``/``session`` let an in-process caller
    (``tools loadtest --warmup-from``) warm against ITS warehouse so
    the executable cache (which keys in-memory tables by identity)
    warms too; standalone runs generate their own at ``sf``/``seed``
    and warm the structural kernel caches + the persistent compile
    cache, which key by shape, not identity."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.datagen import scale_test_specs
    from spark_rapids_tpu.dispatch import COMPILE_SCOPE
    from spark_rapids_tpu.lint.golden import _load_scale_test
    from spark_rapids_tpu.plan.fingerprint import fingerprint, \
        EXECUTABLE_NEUTRAL_PREFIXES
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.tools.report import load_events

    records = load_events(eventlog_dir)
    st = _load_scale_test()
    if tables is None:
        specs = scale_test_specs(sf)
        tables = {name: spec.generate_table(sf, seed=seed)
                  for name, spec in specs.items()}
    if session is None:
        # replays must not append records into the very corpus they
        # read (a site conf pointing eventLog at the serving log dir
        # would otherwise grow it with untagged junk every warmup)
        session = TpuSession({"spark.rapids.sql.eventLog.enabled":
                              "false"})
    build = st.build_sql_queries if use_sql else st.build_queries
    corpus = build(session, tables)

    # distinct work units out of the record stream, preserving order
    units: "Dict[str, dict]" = {}
    unmatched: List[str] = []
    for rec in records:
        name = _corpus_name(rec.get("queryTag"), corpus)
        if name is not None:
            units.setdefault(f"corpus:{name}", {
                "kind": "corpus", "name": name})
            continue
        sql = rec.get("sqlText")
        if sql:
            units.setdefault(f"sql:{sql}", {
                "kind": "sql", "name": rec.get("queryTag") or
                f"query_{rec.get('queryIndex')}", "sql": sql})
            continue
        unmatched.append(str(rec.get("queryTag") or
                             f"query_{rec.get('queryIndex')}"))

    # SQL replays bind against the warehouse as temp views
    if any(u["kind"] == "sql" for u in units.values()):
        from spark_rapids_tpu.plan import from_host_table
        for tname, t in tables.items():
            from_host_table(t, session).create_or_replace_temp_view(tname)

    persistent = bool(srt.ensure_compile_cache())
    seen_plans = set()
    queries: List[dict] = []
    compiled = skipped = failed = 0
    before_all = dict(COMPILE_SCOPE)
    t_start = time.perf_counter()
    for unit in units.values():
        label = unit["name"]
        try:
            if unit["kind"] == "corpus":
                df = corpus[unit["name"]]()
            else:
                df = session.sql(unit["sql"])
            # dedupe by the FULL fingerprint, not the stripped template:
            # numeric literal values trace as XLA constants
            # (Literal.key includes them), so 'price > 5' and
            # 'price > 6' need separate traces — skipping the second as
            # a template-duplicate would leave it cold
            template = fingerprint(
                df.plan, session.conf, strip_literals=False,
                neutral_prefixes=EXECUTABLE_NEUTRAL_PREFIXES)
            if template is not None and template in seen_plans:
                skipped += 1
                queries.append({"query": label, "status": "skipped",
                                "reason": "duplicate plan"})
                continue
            before = dict(COMPILE_SCOPE)
            t0 = time.perf_counter()
            df.collect_table()
            wall = time.perf_counter() - t0
            traces = (COMPILE_SCOPE.get("kernelTraces", 0)
                      - before.get("kernelTraces", 0))
            if template is not None:
                seen_plans.add(template)
            entry = {
                "query": label,
                "status": "compiled" if traces else "warm",
                "newTraces": int(traces),
                "compileMs": float(session.last_compile_ms or 0.0),
                "executableCacheHit":
                    bool(session.last_executable_cache_hit),
                "wallS": round(wall, 4),
            }
            if traces:
                compiled += 1
            else:
                skipped += 1
            queries.append(entry)
        except Exception as exc:  # a bad replay must not stop the rest
            failed += 1
            queries.append({"query": label, "status": "failed",
                            "reason": f"{type(exc).__name__}: {exc}"})
    delta = {k: COMPILE_SCOPE.get(k, 0) - before_all.get(k, 0)
             for k in ("kernelTraces", "kernelTraceCacheHits",
                       "kernelCompileTime", "executableCacheHits",
                       "executableCacheMisses")}
    return {
        "mode": "warmup",
        "eventlogDir": eventlog_dir,
        "scaleFactor": sf,
        "seed": seed,
        "form": "sql" if use_sql else "dsl",
        "eventRecords": len(records),
        "distinctUnits": len(units),
        "unmatchedRecords": sorted(set(unmatched)),
        "persistentCompileCache": persistent,
        "programsCompiled": compiled,
        "programsSkipped": skipped,
        "failures": failed,
        "newTraces": int(delta["kernelTraces"]),
        "compileSTotal": round(float(delta["kernelCompileTime"]), 4),
        "wallS": round(time.perf_counter() - t_start, 4),
        "queries": queries,
        "ok": failed == 0 and len(units) > 0,
    }


def render_warmup(report: dict) -> str:
    lines = [
        f"Warmup: {report['distinctUnits']} distinct templates from "
        f"{report['eventRecords']} event records "
        f"({report['eventlogDir']})",
        f"  programs compiled {report['programsCompiled']}  "
        f"(skipped {report['programsSkipped']}, "
        f"failed {report['failures']})",
        f"  new XLA traces    {report['newTraces']}  "
        f"({report['compileSTotal']:.2f}s compiling, "
        f"wall {report['wallS']:.2f}s)",
        f"  persistent cache  {report['persistentCompileCache']}",
    ]
    for q in report["queries"]:
        if q["status"] == "failed":
            lines.append(f"    {q['query']}: FAILED {q['reason']}")
    if report["unmatchedRecords"]:
        lines.append("  unmatched records: "
                     + ", ".join(report["unmatchedRecords"][:10]))
    return "\n".join(lines)
