"""TpuSession — the user entry point (reference analog: SQLPlugin +
RapidsDriverPlugin/RapidsExecutorPlugin lifecycle, Plugin.scala — SURVEY.md
§2.1/§3.1). Owns the conf, the device runtime, and plan execution through
the overrides engine."""

from __future__ import annotations

import threading
from typing import Dict, Optional

from spark_rapids_tpu.columnar import HostTable
from spark_rapids_tpu.conf import RapidsConf
from spark_rapids_tpu.overrides import apply_overrides, explain_plan
from spark_rapids_tpu.plan import DataFrame, from_host_table
from spark_rapids_tpu.plan import nodes as P


def _kernel_demotions() -> Dict[str, str]:
    """Pallas primitive->HLO demotions for the event record (lazy
    import: the session module must stay importable standalone)."""
    from spark_rapids_tpu import kernels
    return kernels.demoted_ops()


def _mem_budget_peak() -> int:
    """The memory arbiter's peak accounted device bytes (event-log
    schema v10 budgetPeak field; lazy import like _kernel_demotions)."""
    from spark_rapids_tpu.runtime.memory import MEMORY
    return int(MEMORY.peak_bytes())


class _TLQueryState:
    """Per-(session, thread) in-flight query state. A session may run
    queries CONCURRENTLY from query-service worker threads; everything a
    single execute() writes while running (depth, phases, the executed
    tree, the next-query attribution fields harnesses set) must be
    thread-local or two in-flight queries corrupt each other's
    envelope. ``last_*`` reads fall back to the session-wide mirror so
    serial callers on another thread still see the most recent query."""

    __slots__ = ("exec_depth", "next_tag", "next_sql", "next_service",
                 "next_mv_epoch", "stream_deltas", "meta", "phases",
                 "executable",
                 "dispatches", "fault_replays", "event_record",
                 "event_path", "exec_cache_token", "exec_cache_hit",
                 "compile_ms", "pad_waste")

    def __init__(self):
        self.exec_depth = 0
        self.next_tag = None
        self.next_sql = None
        self.next_service = None
        self.next_mv_epoch = None
        self.stream_deltas = None
        self.meta = None
        self.phases = None
        self.executable = None
        self.dispatches = None
        self.fault_replays = None
        self.event_record = None
        self.event_path = None
        self.exec_cache_token = None
        self.exec_cache_hit = None
        self.compile_ms = None
        self.pad_waste = None


def _tl_mirrored(tls_field: str, doc: str):
    """Property: read this thread's value, else the session-wide mirror
    of the last completed query; writes update both."""

    def _get(self):
        v = getattr(self._q, tls_field)
        return v if v is not None else self._mirror.get(tls_field)

    def _set(self, value):
        setattr(self._q, tls_field, value)
        self._mirror[tls_field] = value

    return property(_get, _set, doc=doc)


def _tl_only(tls_field: str, doc: str):
    def _get(self):
        return getattr(self._q, tls_field)

    def _set(self, value):
        setattr(self._q, tls_field, value)

    return property(_get, _set, doc=doc)


class TpuSession:
    # -- per-thread query state (concurrent executes; see _TLQueryState) --
    next_query_tag = _tl_only(
        "next_tag", "query tag the NEXT execute() on this thread records")
    next_query_sql = _tl_only(
        "next_sql", "SQL text the NEXT execute() on this thread records")
    next_query_service = _tl_only(
        "next_service", "service envelope (tenant/pool/queue-wait/"
        "cache-hit) the NEXT execute() on this thread records")
    next_query_mv_epoch = _tl_only(
        "next_mv_epoch", "materialized-view epoch (the maintained "
        "table's Delta version) the NEXT execute() on this thread "
        "records as mvEpoch — set by MV serve paths, null otherwise")

    def stage_stream_delta(self, key: str, n: int = 1) -> None:
        """Attribute streaming work (microBatches/mvRefreshes/.../
        sinkReplays) to the NEXT execute() on this thread: the streaming
        subsystem's bookkeeping runs BETWEEN query envelopes (after one
        execute returns, before the next starts), so the process-wide
        scope deltas alone would never land inside a record's window.
        Drained (and zeroed) by the next record built on this thread."""
        q = self._q
        d = q.stream_deltas or {}
        d[key] = d.get(key, 0) + n
        q.stream_deltas = d
    _exec_depth = _tl_only(
        "exec_depth", "nested-execute depth on this thread")
    _last_meta = _tl_only("meta", "overrides meta of this thread's query")
    _last_phases = _tl_only("phases", "phase times of this thread's query")
    _last_executable = _tl_mirrored(
        "executable", "executed tree of the last query (thread, then "
        "session-wide)")
    last_dispatches = _tl_mirrored(
        "dispatches", "device dispatches of the last query")
    last_fault_replays = _tl_mirrored(
        "fault_replays", "circuit-breaker replays of the last query")
    last_event_record = _tl_mirrored(
        "event_record", "event-log record of the last query")
    last_event_path = _tl_mirrored(
        "event_path", "event-log path of the last query")
    last_executable_cache_hit = _tl_mirrored(
        "exec_cache_hit", "did the last query check out a cached "
        "converted executable (plan/executable_cache.py)?")
    last_compile_ms = _tl_mirrored(
        "compile_ms", "milliseconds the last query spent on new XLA "
        "traces (trace + lowering + backend compile)")
    last_pad_waste_rows = _tl_mirrored(
        "pad_waste", "dead tail rows the last query uploaded to pad "
        "batches up to their capacity buckets")

    def __init__(self, conf: Optional[Dict] = None):
        self.conf = RapidsConf(conf)
        self._runtime = None
        self._profiler = None
        self._catalog = None
        # observability state (obs/): per-session query sequence, the
        # lazy event-log writer, and the caller-settable attribution
        # fields the next execute() consumes (harnesses tag queries so
        # the offline tools can match runs per query). In-flight query
        # state is per-thread (_TLQueryState); _mirror keeps the
        # last-completed-query view for readers on other threads.
        self._tls = threading.local()
        self._mirror: Dict[str, object] = {}
        self._obs_lock = threading.Lock()
        self._obs_query_seq = 0
        self._event_writer = None
        self._placement = None

    @property
    def _q(self) -> _TLQueryState:
        q = getattr(self._tls, "q", None)
        if q is None:
            q = self._tls.q = _TLQueryState()
        return q

    # -- SQL front end -------------------------------------------------------
    @property
    def catalog(self):
        """Session catalog: temp views, registered file-format tables
        (sources SPI) and SQL-callable functions."""
        if self._catalog is None:
            from spark_rapids_tpu.sql.catalog import SessionCatalog
            self._catalog = SessionCatalog(self)
        return self._catalog

    def sql(self, text: str) -> DataFrame:
        """Run one SQL statement (SELECT / CREATE TEMP VIEW / DROP VIEW)
        through parser -> analyzer -> the existing plan layer; the
        resulting DataFrame flows through overrides/AQE exactly like a
        DSL-built one."""
        from spark_rapids_tpu.sql import lower_statement
        df = lower_statement(self, text)
        df.sql_text = text
        return df

    def table(self, name: str) -> DataFrame:
        """DataFrame over a temp view or registered table."""
        return self.catalog.table(name)

    @property
    def placement(self):
        """The placement half of the session split
        (runtime/placement.py): mesh realization, device-residency
        gating, the speculative drain and async-fetch resolution. This
        class keeps the DRIVER half — SQL/catalog, planning,
        overrides/AQE, verification, caches, observability."""
        if self._placement is None:
            from spark_rapids_tpu.runtime.placement import PlacementLayer
            self._placement = PlacementLayer(self)
        return self._placement

    @property
    def profiler(self):
        if self._profiler is None:
            from spark_rapids_tpu.runtime.profiler import TpuProfiler
            self._profiler = TpuProfiler(self.conf)
        return self._profiler

    # -- lifecycle ----------------------------------------------------------
    @property
    def runtime(self):
        if self._runtime is None:
            from spark_rapids_tpu.runtime.device_manager import TpuDeviceManager
            self._runtime = TpuDeviceManager(self.conf)
            self._runtime.initialize()
        return self._runtime

    def set_conf(self, key: str, value) -> "TpuSession":
        self.conf = self.conf.set(key, value)
        return self

    # -- data sources -------------------------------------------------------
    def create_dataframe(self, data, dtypes=None, num_batches: int = 1) -> DataFrame:
        if isinstance(data, HostTable):
            return from_host_table(data, self, num_batches)
        if isinstance(data, dict):
            return from_host_table(HostTable.from_pydict(data, dtypes), self, num_batches)
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return from_host_table(HostTable.from_pandas(data), self, num_batches)
        raise TypeError(f"cannot create DataFrame from {type(data)}")

    def range(self, start: int, end: Optional[int] = None, step: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(P.RangeNode(start, end, step), self)

    def read_parquet(self, *paths, **options) -> DataFrame:
        from spark_rapids_tpu.io.parquet import ParquetScanNode
        return DataFrame(ParquetScanNode(list(paths), self.conf, **options), self)

    def read_csv(self, *paths, **options) -> DataFrame:
        from spark_rapids_tpu.io.csv import CsvScanNode
        return DataFrame(CsvScanNode(list(paths), self.conf, **options), self)

    def read_json(self, *paths, **options) -> DataFrame:
        from spark_rapids_tpu.io.json import JsonScanNode
        return DataFrame(JsonScanNode(list(paths), self.conf, **options), self)

    def read_orc(self, *paths, **options) -> DataFrame:
        from spark_rapids_tpu.io.orc import OrcScanNode
        return DataFrame(OrcScanNode(list(paths), self.conf, **options), self)

    # connectors resolve through the provider SPI (sources.py —
    # ExternalSource.scala analog), never by direct import here
    @property
    def read(self):
        """session.read.format("delta").load(path) — reader surface
        routed through the external-source provider SPI."""
        from spark_rapids_tpu.sources import DataFrameReader
        return DataFrameReader(self)

    def read_format(self, fmt: str, *paths, **options) -> DataFrame:
        from spark_rapids_tpu.sources import create_scan
        return DataFrame(create_scan(fmt, list(paths), self.conf,
                                     **options), self)

    def read_delta(self, path, version_as_of=None, **options) -> DataFrame:
        return self.read_format("delta", path,
                                version_as_of=version_as_of, **options)

    def delta_table(self, path) -> "object":
        from spark_rapids_tpu.errors import ColumnarProcessingError
        from spark_rapids_tpu.sources import provider_for
        p = provider_for("delta")
        if p is None:
            raise ColumnarProcessingError(
                "delta source provider is not available")
        return p.create_table_api(self, path)

    def read_iceberg(self, path, snapshot_id=None, **options) -> DataFrame:
        return self.read_format("iceberg", path, snapshot_id=snapshot_id,
                                **options)

    def read_avro(self, *paths, **options) -> DataFrame:
        return self.read_format("avro", *paths, **options)

    def read_hive_text(self, *paths, schema=None, **options) -> DataFrame:
        return self.read_format("hive-text", *paths, schema=schema,
                                **options)

    # -- execution ----------------------------------------------------------
    def execute(self, plan: P.PlanNode) -> HostTable:
        """Run one query: recovery-wrapped execution plus the per-query
        observability envelope — when the event log or host tracing is
        enabled, spans collect for the duration and a structured record
        (obs/events.py) is written on success. Nested executes
        (cached-relation / broadcast materialization inside an outer
        query) ride the outer envelope. Safe to call concurrently from
        multiple threads (the query service's worker pool): in-flight
        state is thread-local and the span tracer scopes each query to
        its executing thread."""
        import time as _time

        from spark_rapids_tpu.obs import events as E
        from spark_rapids_tpu.obs.spans import (
            TRACE_DIR,
            TRACE_ENABLED,
            TRACER,
        )

        q = self._q
        query_tag, q.next_tag = q.next_tag, None
        sql_text, q.next_sql = q.next_sql, None
        service_info, q.next_service = q.next_service, None
        mv_epoch, q.next_mv_epoch = q.next_mv_epoch, None
        stream_deltas, q.stream_deltas = (q.stream_deltas or {}), None

        if not q.exec_depth:
            # fresh per-host scan attribution for this top-level query
            # (thread-local, like the dispatch counters — nested
            # executes accumulate into the outer query's table)
            from spark_rapids_tpu.runtime.cluster import (
                reset_host_scan_stats,
            )
            reset_host_scan_stats()

        if q.exec_depth:
            # nested query: no separate envelope, no index
            q.exec_depth += 1
            try:
                return self._execute_with_recovery(plan)
            finally:
                q.exec_depth -= 1

        ev_enabled = bool(self.conf.get_entry(E.EVENT_LOG_ENABLED))
        tr_enabled = bool(self.conf.get_entry(TRACE_ENABLED))
        obs_active = ev_enabled or tr_enabled
        # this thread's view while THIS query is in flight: no record
        # yet (readers fall back to the session-wide mirror of the last
        # completed query)
        q.event_record = None
        q.event_path = None
        with self._obs_lock:
            qidx = self._obs_query_seq
            self._obs_query_seq += 1
        if obs_active:
            from spark_rapids_tpu.obs.metrics import scopes_snapshot
            from spark_rapids_tpu.runtime.faults import FAULTS, RECOVERY
            from spark_rapids_tpu.runtime.health import HEALTH
            before_scopes = scopes_snapshot()
            before_recovery = RECOVERY.snapshot()
            before_fires = FAULTS.counters()
            before_health = HEALTH.snapshot()
            ctx = TRACER.begin_query(qidx)
        else:
            # no envelope for THIS query, but another session's
            # observed query may be live on a worker thread: block the
            # tracer's helper-thread adoption so this query's spans
            # can't pollute that query's record
            TRACER.begin_unobserved_query()
        q.exec_depth = 1
        t0 = _time.perf_counter()
        try:
            result = self._execute_with_recovery(plan)
        except BaseException:
            if obs_active:
                TRACER.end_query()
            # a failed run may have left the checked-out tree partially
            # drained — drop the entry, never hand it to another query
            self._release_exec_cache(drop=True)
            raise
        finally:
            q.exec_depth = 0
            if not obs_active:
                TRACER.end_unobserved_query()
            # success OR failure: a WriteFiles plan that failed
            # mid-drain may still have changed on-disk contents, so
            # cached results over its paths are stale either way
            self._invalidate_result_cache_on_write(plan)
        if not obs_active:
            self._release_exec_cache()
            return result
        wall_s = _time.perf_counter() - t0
        spans = TRACER.end_query()

        from spark_rapids_tpu.obs.metrics import scopes_snapshot
        from spark_rapids_tpu.obs.spans import (
            finalize_observation,
            summarize_spans,
            write_chrome_trace,
        )
        from spark_rapids_tpu.runtime.faults import (
            CIRCUIT_BREAKER,
            FAULTS,
            RECOVERY,
        )
        from spark_rapids_tpu.runtime.health import HEALTH
        executable = q.executable
        if executable is not None:
            finalize_observation(executable)
        after_recovery = RECOVERY.snapshot()
        after_fires = FAULTS.counters()
        after_health = HEALTH.snapshot()
        after_scopes = scopes_snapshot()
        # worker restarts ride the process-wide ``health`` scope (the
        # service's watchdog respawns workers while queries run), so
        # the per-record delta attributes restarts to the wall they
        # happened under (0 on a quiet process)
        worker_restarts = int(
            after_scopes.get("health", {}).get("workersRespawned", 0)
            - before_scopes.get("health", {}).get("workersRespawned", 0))

        # transactional-write accounting: per-record deltas of the
        # ``write`` scope (io/committer.py) — the committer/Delta
        # transaction counters are process-wide, so the delta
        # attributes files/bytes/retries to the query whose wall they
        # happened under (all 0 for read-only queries)
        def _wdelta(key: str, scope: str = "write") -> int:
            return int(after_scopes.get(scope, {}).get(key, 0)
                       - before_scopes.get(scope, {}).get(key, 0))

        from spark_rapids_tpu.parallel.mesh import MESH
        from spark_rapids_tpu.runtime.cluster import (
            CLUSTER,
            host_scan_stats,
        )

        record = E.build_query_record(
            query_index=qidx,
            wall_s=wall_s,
            phases=q.phases or {},
            executable=executable,
            meta=q.meta,
            sql_text=sql_text,
            query_tag=query_tag,
            dispatches=int(q.dispatches or 0),
            recovery_delta={k: v - before_recovery.get(k, 0)
                            for k, v in after_recovery.items()
                            if v - before_recovery.get(k, 0)},
            scope_deltas=E.scope_delta(before_scopes, after_scopes),
            fault_fires={k: v - before_fires.get(k, 0)
                         for k, v in after_fires.items()
                         if v - before_fires.get(k, 0)},
            # exec circuit-breaker demotions + Pallas kernel->HLO
            # demotions in one map (keys 'pallas:<primitive>'), so the
            # offline tools see both without a schema change
            demotions={**CIRCUIT_BREAKER.demoted_ops(),
                       **_kernel_demotions()},
            spans_summary=summarize_spans(spans, ctx.owner_tid, wall_s),
            fault_replays=int(q.fault_replays or 0),
            service=service_info,
            compile_ms=float(q.compile_ms or 0.0),
            executable_cache_hit=bool(q.exec_cache_hit),
            pad_waste_rows=int(q.pad_waste or 0),
            health_state=HEALTH.state(),
            device_reinits=int(after_health["deviceReinits"]
                               - before_health["deviceReinits"]),
            worker_restarts=worker_restarts,
            files_written=_wdelta("filesWritten"),
            bytes_written=_wdelta("bytesWritten"),
            commit_retries=_wdelta("commitRetries"),
            mesh_shape=MESH.shape_str(),
            ici_bytes=_wdelta("iciBytes", "mesh"),
            mesh_degradations=_wdelta("meshDegradations", "health"),
            shard_retries=_wdelta("shardRetries", "mesh"),
            gather_checks_failed=_wdelta("gatherChecksFailed", "mesh"),
            host_topology=CLUSTER.topology_str(),
            hosts_lost=_wdelta("hostsLost", "cluster"),
            host_relands=_wdelta("hostRelands", "cluster"),
            dcn_exchanges=_wdelta("dcnExchanges", "cluster"),
            host_scans=host_scan_stats(),
            oom_retries=_wdelta("oomRetries", "memory"),
            split_retries=_wdelta("splitRetries", "memory"),
            spill_bytes=_wdelta("spillBytes", "memory"),
            unspills=_wdelta("unspills", "memory"),
            budget_peak=_mem_budget_peak(),
            # streaming attribution: scope deltas (work done INSIDE
            # this window) plus the deltas the streaming subsystem
            # staged on this thread between envelopes
            micro_batches=_wdelta("microBatches", "streaming")
            + stream_deltas.get("microBatches", 0),
            mv_refreshes=_wdelta("mvRefreshes", "streaming")
            + stream_deltas.get("mvRefreshes", 0),
            mv_incremental_refreshes=_wdelta(
                "mvIncrementalRefreshes", "streaming")
            + stream_deltas.get("mvIncrementalRefreshes", 0),
            mv_full_recomputes=_wdelta("mvFullRecomputes", "streaming")
            + stream_deltas.get("mvFullRecomputes", 0),
            sink_commits=_wdelta("sinkCommits", "streaming")
            + stream_deltas.get("sinkCommits", 0),
            sink_replays=_wdelta("sinkReplays", "streaming")
            + stream_deltas.get("sinkReplays", 0),
            mv_epoch=mv_epoch,
        )
        self.last_event_record = record
        # the record has read the tree's metrics — the cached executable
        # may now be handed to the next query (which resets them)
        self._release_exec_cache()
        # emission is best-effort: an unwritable log dir or full disk
        # must not fail a query that already computed its result
        try:
            if ev_enabled:
                self._write_event_record(record)
            if tr_enabled:
                import os
                trace_dir = str(self.conf.get_entry(TRACE_DIR))
                os.makedirs(trace_dir, exist_ok=True)
                write_chrome_trace(
                    os.path.join(trace_dir, f"query_{qidx}.trace.json"),
                    spans, query_id=qidx)
        except OSError as exc:
            print(f"spark_rapids_tpu: event/trace emission failed "
                  f"(query {qidx}): {exc}")
        return result

    def _write_event_record(self, record: dict) -> str:
        """THE event-log append path — lazily creates the per-session
        writer under the obs lock. Used by execute() and by the query
        service's cache-hit record emission, so writer setup can never
        diverge between executed and served queries. Raises OSError on
        emission failure; callers treat it as best-effort."""
        from spark_rapids_tpu.obs import events as E
        with self._obs_lock:
            if self._event_writer is None:
                self._event_writer = E.QueryEventWriter(
                    str(self.conf.get_entry(E.EVENT_LOG_DIR)))
        # the flight recorder's "recent events" context rides the same
        # funnel (slim summary, bounded ring — obs/events.py)
        E.note_recent_record(record)
        path = self._event_writer.write(record)
        self.last_event_path = path
        return path

    def _release_exec_cache(self, drop: bool = False) -> None:
        """Return this thread's checked-out executable-cache entry (if
        any). Called once the query's envelope is fully done with the
        tree — after the event record on observed queries — or with
        ``drop`` when the run failed and the tree's state is suspect."""
        tok = self._q.exec_cache_token
        self._q.exec_cache_token = None
        if tok is not None:
            tok.release(drop=drop)

    def _invalidate_result_cache_on_write(self, plan: P.PlanNode) -> None:
        """A completed write (WriteFiles / Delta / Iceberg commands ride
        plans or commit through delta.log, which bumps the epoch itself)
        invalidates every cached service result — contents under the
        written paths changed."""
        stack = [plan]
        while stack:
            node = stack.pop()
            if isinstance(node, P.WriteFiles):
                from spark_rapids_tpu.service.result_cache import (
                    bump_invalidation_epoch,
                )
                bump_invalidation_epoch("WriteFiles")
                return
            stack.extend(getattr(node, "children", ()))

    def _execute_with_recovery(self, plan: P.PlanNode) -> HostTable:
        """Plan, verify, and drain a query — wrapped in TWO distinct
        recovery layers:

        * a non-OOM KERNEL failure (KernelCrashError) replays the query
          through the runtime circuit breaker, and once the same
          operator fails spark.rapids.sql.runtimeFallback.maxFailures
          times it is demoted to the CPU fallback path for the session
          (the replay re-plans, so the demotion takes effect
          immediately);
        * a FATAL device error (is_fatal_device_error — the device or
          its tunnel is gone, not one operator) captures a crash report
          and hands recovery to the health monitor (runtime/health.py):
          backend reinit, device-referencing caches invalidated, and
          after deviceLoss.maxReinits consecutive losses the CPU-only
          latch. The query surfaces a typed RETRYABLE DeviceLostError —
          the query service requeues it against the recovered backend.

        OOMs never come through here — the retry framework owns those."""
        from spark_rapids_tpu.conf import (
            RUNTIME_FALLBACK_ENABLED,
            RUNTIME_FALLBACK_MAX_FAILURES,
            TEST_FAULTS,
        )
        from spark_rapids_tpu.errors import DeviceLostError, KernelCrashError
        from spark_rapids_tpu.runtime import faults as F
        from spark_rapids_tpu.runtime.crash_handler import (
            handle_fatal,
            is_fatal_device_error,
        )

        F.FAULTS.arm(str(self.conf.get_entry(TEST_FAULTS) or ""))
        # telemetry sampler + flight-recorder defaults follow this
        # session's conf (cheap no-op when unchanged, the arm contract)
        from spark_rapids_tpu.obs.telemetry import TELEMETRY
        TELEMETRY.configure(self.conf)
        # the device memory arbiter's hard budget follows it too
        from spark_rapids_tpu.runtime import memory as _memory
        _memory.MEMORY.configure(self.conf)
        # runtime lock witness (construction-time election — locks
        # built after this point are wrapped iff the conf arms it)
        from spark_rapids_tpu import lockorder as _lockorder
        _lockorder.configure(self.conf)
        rf_enabled = bool(self.conf.get_entry(RUNTIME_FALLBACK_ENABLED))
        max_failures = int(self.conf.get_entry(RUNTIME_FALLBACK_MAX_FAILURES))
        # enough budget to demote every op in a pathological plan without
        # ever replaying unboundedly on an unattributable crash
        max_replays = 4 * max_failures + 4
        replays = 0
        # mesh degradation ladder (runtime/health.py): PARTIAL device
        # losses replay internally — enough budget to walk every rung
        # (retry -> single-device -> every shrink -> every reinit ->
        # the CPU-only latch) without replaying unboundedly
        from contextlib import nullcontext

        from spark_rapids_tpu.errors import (
            HostLostError,
            MeshDeviceLostError,
        )
        from spark_rapids_tpu.parallel import mesh as _mesh
        from spark_rapids_tpu.runtime import cluster as _cluster
        from spark_rapids_tpu.runtime.health import DEVICE_LOSS_MAX_REINITS
        max_mesh_replays = (
            int(self.conf.get_entry(_mesh.MESH_DEGRADE_MAX_SHRINKS))
            + int(self.conf.get_entry(DEVICE_LOSS_MAX_REINITS)) + 6)
        mesh_replays = 0
        # host degradation ladder (runtime/health.py on_host_loss):
        # enough budget to walk every rung (retry -> reland -> every
        # shrink -> the single-process latch) plus escalation slack
        max_host_replays = (
            int(self.conf.get_entry(_cluster.CLUSTER_MAX_HOST_LOSSES))
            + int(self.conf.get_entry(DEVICE_LOSS_MAX_REINITS)) + 6)
        host_replays = 0
        # memory degradation ladder (runtime/health.py
        # on_memory_pressure): FatalDeviceOOMs that escaped the retry
        # framework replay internally — enough budget to walk every
        # rung (full-spill retry -> chunked re-execution -> one CPU
        # demotion per plan operator) without replaying unboundedly
        max_mem_replays = 4 * max_failures + 4
        mem_replays = 0
        suppress_reason = None
        suppress_cluster = None
        force_chunk = None
        while True:
            was_suppressed = suppress_reason is not None
            was_csuppressed = suppress_cluster is not None
            attempt_ctx = (_mesh.suppressed_mesh(suppress_reason)
                           if was_suppressed else nullcontext())
            cluster_ctx = (_cluster.suppressed_cluster(suppress_cluster)
                           if was_csuppressed else nullcontext())
            from spark_rapids_tpu.runtime import memory as _memory
            chunk_ctx = (_memory.forced_chunking(force_chunk)
                         if force_chunk is not None else nullcontext())
            suppress_reason = None
            suppress_cluster = None
            force_chunk = None
            try:
                with attempt_ctx, cluster_ctx, chunk_ctx:
                    result = self._execute_attempt(plan)
                self.last_fault_replays = replays
                if replays and hasattr(self._last_executable, "metrics"):
                    self._last_executable.metrics["runtimeFaultReplays"] = \
                        replays
                from spark_rapids_tpu.runtime.health import HEALTH
                # the MESH ladder only resets on a mesh-NATIVE success:
                # a suppressed (single-device) convergence proves
                # nothing about the mesh's health — and the HOST ladder
                # likewise only on a cluster-NATIVE success
                HEALTH.note_success(
                    mesh_native=not was_suppressed and _mesh.MESH.enabled,
                    cluster_native=(not was_csuppressed
                                    and _cluster.CLUSTER.active()))
                return result
            except Exception as exc:
                from spark_rapids_tpu.errors import FatalDeviceOOM
                from spark_rapids_tpu.runtime.retry import is_device_oom
                if (is_device_oom(exc)
                        and not isinstance(exc, FatalDeviceOOM)
                        and not getattr(exc, "_mem_handled", False)):
                    # a RETRYABLE OOM that escaped every retry wrapper
                    # (a landing site without its own retry_block):
                    # the memory ladder is strictly better than
                    # failing the query — normalize and fall through
                    # to the FatalDeviceOOM branch below
                    wrapped = FatalDeviceOOM(
                        f"unhandled retryable OOM escaped to the "
                        f"session ({type(exc).__name__}: {exc})")
                    wrapped.__cause__ = exc
                    if getattr(exc, "fault_op", None) is not None:
                        wrapped.fault_op = exc.fault_op
                    exc = wrapped
                if isinstance(exc, FatalDeviceOOM) and \
                        not getattr(exc, "_mem_handled", False):
                    # the retry framework is out of moves (spill
                    # replays AND split-and-retry both exhausted): the
                    # MEMORY degradation ladder owns the attempt —
                    # full-spill retry, then chunked re-execution,
                    # then per-op CPU demotion, each action recording
                    # a flight-recorder incident bundle
                    from spark_rapids_tpu.runtime.health import HEALTH
                    action = HEALTH.on_memory_pressure(exc, self.conf)
                    if action == "abort" or mem_replays >= max_mem_replays:
                        exc._mem_handled = True
                        raise
                    if self._q.exec_depth == 1:
                        self._release_exec_cache(drop=True)
                    mem_replays += 1
                    F.RECOVERY.bump("query_replays")
                    if action == "chunk":
                        # replay with scans forced onto chunks half
                        # the normal budget share — bounded partitions
                        # stream where one batch could not fit
                        force_chunk = max(
                            1, _memory.MEMORY.scan_chunk_bytes() // 2)
                    # "retry" replays same-shape after the full spill;
                    # "cpu_demote" re-plans with the attributed op
                    # demoted to the CPU path (circuit breaker)
                    continue
                if isinstance(exc, HostLostError) and \
                        not getattr(exc, "_health_handled", False):
                    # a whole executor HOST died (the local backend is
                    # fine): the HOST degradation ladder owns recovery
                    # — classified before the whole-backend is_fatal
                    # branch (HostLostError IS a DeviceLostError)
                    from spark_rapids_tpu.runtime.health import HEALTH
                    action = HEALTH.on_host_loss(exc, self.conf)
                    self._strike_fault_template(
                        plan, exc, action, domain="host",
                        benign=("retry",))
                    if host_replays >= max_host_replays:
                        exc._health_handled = True
                        raise
                    if self._q.exec_depth == 1:
                        self._release_exec_cache(drop=True)
                    host_replays += 1
                    F.RECOVERY.bump("query_replays")
                    if action in ("single_process", "DEGRADED",
                                  "CPU_ONLY"):
                        # pin the replay to local scans even if a host
                        # rejoins (clearing the latch) mid-attempt —
                        # the attempt must be deterministic. The
                        # escalated actions replay too (the mesh
                        # branch's contract): the re-plan sees the
                        # reinitialized backend or the CPU-only latch
                        # and serves the query without the cluster.
                        suppress_cluster = HEALTH.host_demotion_note()
                    # "retry"/"reland"/"shrink" replay plain: the
                    # re-plan's scans see the re-routed topology
                    continue
                if isinstance(exc, MeshDeviceLostError) and \
                        not getattr(exc, "_health_handled", False):
                    # PARTIAL loss (one mesh device dead, backend
                    # alive): the degradation ladder owns recovery —
                    # classified DISTINCTLY from the whole-backend
                    # is_fatal branch below
                    from spark_rapids_tpu.runtime.health import HEALTH
                    action = HEALTH.on_mesh_device_loss(exc, self.conf)
                    self._strike_fault_template(plan, exc, action,
                                                domain="mesh")
                    if mesh_replays >= max_mesh_replays:
                        exc._health_handled = True
                        raise
                    if self._q.exec_depth == 1:
                        self._release_exec_cache(drop=True)
                    mesh_replays += 1
                    F.RECOVERY.bump("query_replays")
                    if action == "single_device":
                        suppress_reason = HEALTH.mesh_demotion_note()
                    # "retry"/"shrink"/"DEGRADED"/"CPU_ONLY" all replay
                    # plain: the re-plan sees the shrunken mesh, the
                    # reinitialized backend, or the CPU-only latch
                    continue
                if is_fatal_device_error(exc):
                    # a nested execute already ran recovery for this
                    # exception — the outer envelope just propagates it
                    if getattr(exc, "_health_handled", False):
                        raise
                    ex = getattr(self, "_last_executable", None)
                    handle_fatal(exc, self.conf,
                                 plan_description=ex.tree_string()
                                 if ex is not None else "")
                    # the in-flight tree references the dead device —
                    # drop it before recovery clears the cache (TOP
                    # LEVEL only: depth >= 2 holds no token)
                    if self._q.exec_depth == 1:
                        self._release_exec_cache(drop=True)
                    from spark_rapids_tpu.runtime.health import HEALTH
                    HEALTH.on_device_loss(exc, self.conf)
                    if isinstance(exc, DeviceLostError):
                        exc._health_handled = True
                        raise
                    lost = DeviceLostError(
                        f"device lost during execution "
                        f"({type(exc).__name__}: {exc}); backend "
                        f"recovered — retry the query")
                    lost._health_handled = True
                    if getattr(exc, "fault_op", None) is not None:
                        lost.fault_op = exc.fault_op
                    raise lost from exc
                demotable = isinstance(exc, KernelCrashError)
                if not rf_enabled or not demotable or replays >= max_replays:
                    raise
                op = getattr(exc, "fault_op", None)
                if op is not None:
                    F.CIRCUIT_BREAKER.record_failure(op, exc, max_failures)
                # the crashed attempt's cached/filled executable is
                # suspect AND a recorded demotion must re-plan — drop
                # the entry so the replay converts fresh. TOP LEVEL
                # only: a nested execute's recovery (depth >= 2) holds
                # no token of its own and must not release the OUTER
                # query's mid-run
                if self._q.exec_depth == 1:
                    self._release_exec_cache(drop=True)
                replays += 1
                F.RECOVERY.bump("query_replays")

    def _strike_fault_template(self, plan: P.PlanNode, exc: BaseException,
                               action: str, domain: str = "mesh",
                               benign=("retry",)) -> None:
        """A template that repeatedly kills mesh or cluster execution
        is a poison suspect like any worker/device killer: every
        ladder action past the plain retry records a quarantine strike
        (the service then refuses the template at admission once it
        crosses spark.rapids.service.quarantine.maxStrikes).
        Best-effort — strike accounting must never mask recovery."""
        if action in benign:
            return
        try:
            from spark_rapids_tpu.plan.fingerprint import (
                template_fingerprint,
            )
            from spark_rapids_tpu.runtime.health import (
                QUARANTINE,
                QUARANTINE_MAX_STRIKES,
            )
            first = (str(exc).splitlines()[0] if str(exc)
                     else type(exc).__name__)
            QUARANTINE.strike(
                template_fingerprint(plan, self.conf),
                f"{domain} execution killed ({action}): "
                f"{type(exc).__name__}: {first}",
                int(self.conf.get_entry(QUARANTINE_MAX_STRIKES)))
        except Exception:
            pass

    def _execute_attempt(self, plan: P.PlanNode) -> HostTable:
        import time as _time

        from spark_rapids_tpu.obs.spans import TRACER

        t_phase = _time.perf_counter()
        plan_span = TRACER.begin("plan", "phase") if TRACER.enabled else None
        try:
            return self._plan_and_drain(plan, plan_span, t_phase)
        except BaseException:
            # a mid-phase failure (plan verify error, conversion bug)
            # must not leave the phase span dangling on the stack
            TRACER.end(plan_span)
            raise

    def _plan_and_drain(self, plan: P.PlanNode, plan_span,
                        t_phase: float) -> HostTable:
        import time as _time

        from spark_rapids_tpu.conf import (
            RETRY_OOM_MAX_RETRIES,
            TEST_INJECT_RETRY_OOM,
        )
        from spark_rapids_tpu.obs.spans import TRACER
        from spark_rapids_tpu.runtime import RMM_TPU
        from spark_rapids_tpu.runtime.retry import MAX_RETRIES_VAR

        from spark_rapids_tpu.overrides.input_file import \
            rewrite_input_file_exprs
        plan = rewrite_input_file_exprs(plan)

        # placement first: the mesh runtime must reflect THIS query's
        # spark.rapids.mesh.* conf before the fingerprint folds the
        # mesh identity token and the executable cache stamps its
        # generation (a reconfiguration invalidates cached trees)
        self.placement.prepare()

        # plan -> executable cache (plan/executable_cache.py): a
        # repeated template checks out its already-converted (and
        # already-verified: planVerify.mode folds into the fingerprint)
        # tree — no overrides run, no verification, and every kernel
        # already traced. Top-level queries only; a replayed attempt
        # dropped its entry in _execute_with_recovery and plans fresh
        # so circuit-breaker demotions take effect.
        q = self._q
        from spark_rapids_tpu.conf import (
            EXECUTABLE_CACHE_ENABLED,
            EXECUTABLE_CACHE_MAX_PLANS,
            EXECUTABLE_CACHE_MAX_VARIANTS,
        )
        tok = None
        if q.exec_depth == 1 and \
                bool(self.conf.get_entry(EXECUTABLE_CACHE_ENABLED)):
            from spark_rapids_tpu.plan.executable_cache import EXEC_CACHE
            EXEC_CACHE.configure(
                int(self.conf.get_entry(EXECUTABLE_CACHE_MAX_PLANS)),
                int(self.conf.get_entry(EXECUTABLE_CACHE_MAX_VARIANTS)))
            tok = EXEC_CACHE.checkout(plan, self.conf)
            q.exec_cache_token = tok
        if q.exec_depth == 1:
            # top level only: a nested execute (cached-relation /
            # broadcast materialization) must not clobber the OUTER
            # query's hit flag
            self.last_executable_cache_hit = bool(
                tok is not None and tok.hit)

        if tok is not None and tok.hit:
            executable, meta = tok.executable, tok.meta
        else:
            executable, meta = apply_overrides(plan, self.conf)
        self._last_meta = meta
        if meta is not None and self.conf.explain_mode in ("NOT_ON_GPU",
                                                           "ALL"):
            print(meta.explain(
                only_fallback=self.conf.explain_mode == "NOT_ON_GPU"))

        if tok is None or not tok.hit:
            # static plan verification (lint/plan_verifier): prove the
            # converted tree's cross-layer invariants BEFORE execution
            # (Catalyst validatePlan / assert-on-fallback analog)
            from spark_rapids_tpu.conf import PLAN_VERIFY_MODE
            verify_mode = str(self.conf.get_entry(PLAN_VERIFY_MODE)).lower()
            if verify_mode not in ("off", "warn", "error"):
                from spark_rapids_tpu.errors import ColumnarProcessingError
                raise ColumnarProcessingError(
                    f"spark.rapids.sql.planVerify.mode must be off, warn or "
                    f"error, got {verify_mode!r}")
            if verify_mode in ("warn", "error") and meta is not None:
                from spark_rapids_tpu.lint.plan_verifier import \
                    verify_converted
                diags = verify_converted(executable, meta, self.conf)
                if diags:
                    from spark_rapids_tpu.errors import PlanVerificationError
                    if verify_mode == "error":
                        raise PlanVerificationError(diags)
                    for d in diags:
                        print(f"planVerify: {d}")

        from spark_rapids_tpu.conf import METRICS_LEVEL
        from spark_rapids_tpu.execs.base import set_metrics_level
        set_metrics_level(self.conf.get_entry(METRICS_LEVEL))

        # rand(seed)/monotonically_increasing_id reproduce per query
        from spark_rapids_tpu.ops.misc import reset_nondeterministic_streams
        reset_nondeterministic_streams()

        # LORE: number every operator; arm input dumping for tagged ids
        # — FRESH trees only. A cached tree keeps the ids and _TeeChild
        # dumpers it was filled with (lore conf folds into the
        # executable fingerprint, so they match this query's conf);
        # re-numbering would shift ids across inserted dumper nodes and
        # install_dumpers is not idempotent (wrappers would stack)
        if tok is None or not tok.hit:
            from spark_rapids_tpu import lore
            lore.assign_lore_ids(executable)
            lore.install_dumpers(executable, self.conf)
        # fault boundaries: the exec.execute injection point + op
        # attribution for non-OOM device failures (circuit breaker input)
        from spark_rapids_tpu.runtime.faults import install_fault_boundaries
        install_fault_boundaries(executable)
        # observation boundaries OVER the fault guards: per-pull spans +
        # the ESSENTIAL opTime/numOutputRows/numOutputBatches metrics on
        # every device exec (obs/spans.py)
        from spark_rapids_tpu.obs.spans import install_observation
        install_observation(executable)
        # cancellation boundaries OUTERMOST (third wrapper in the
        # install_fault_boundaries family): the boundary resolves the
        # ACTIVE cancel scope per pull (contextvar), so it is installed
        # unconditionally — a cached executable filled by a scopeless
        # query still honors cancel()/deadlines when the query service
        # reuses it (service/query.py)
        from spark_rapids_tpu.service.query import install_cancellation
        install_cancellation(executable)
        self._last_executable = executable
        TRACER.end(plan_span)
        phases = {"planS": _time.perf_counter() - t_phase}

        inject = str(self.conf.get_entry(TEST_INJECT_RETRY_OOM) or "")
        if inject:
            kind, _, num = inject.partition(":")
            count = int(num) if num else 1
            if kind.strip().lower() == "retry":
                RMM_TPU.force_retry_oom(count)
            elif kind.strip().lower() == "split":
                RMM_TPU.force_split_and_retry_oom(count)

        # async result fetch: arm the ROOT transition only — mid-plan
        # DeviceToHost nodes feed CPU fallback operators that expect
        # plain host batches. Re-set either way so a cached executable
        # never carries a previous query's flag.
        from spark_rapids_tpu.conf import ASYNC_RESULT_FETCH
        from spark_rapids_tpu.execs.base import DeviceToHost as _D2H
        if isinstance(executable, _D2H):
            executable._async_fetch = bool(
                self.conf.get_entry(ASYNC_RESULT_FETCH))

        token = MAX_RETRIES_VAR.set(self.conf.get_entry(RETRY_OOM_MAX_RETRIES))
        from spark_rapids_tpu.dispatch import (
            dispatch_count,
            reset_compile_stats,
            reset_dispatch_count,
        )
        reset_dispatch_count()
        if q.exec_depth == 1:
            # top level only: a NESTED execute resetting mid-drain
            # would zero the outer query's trace/pad-waste accounting
            reset_compile_stats()
        t_phase = _time.perf_counter()
        exec_span = TRACER.begin("execute", "phase") \
            if TRACER.enabled else None
        try:
            with self.profiler.profile_query():
                # placement owns the drain: device-residency gating
                # (semaphore), speculation, async-fetch resolution
                batches = self.placement.drain(executable)
            # per-query device dispatch count (VERDICT r3: observable)
            self.last_dispatches = dispatch_count()
            if hasattr(executable, "metrics"):
                executable.metrics["dispatches"] = self.last_dispatches
        finally:
            MAX_RETRIES_VAR.reset(token)
            TRACER.end(exec_span)
            phases["executeS"] = _time.perf_counter() - t_phase
            self._last_phases = phases
        t_phase = _time.perf_counter()
        collect_span = TRACER.begin("collect", "phase") \
            if TRACER.enabled else None
        try:
            if not batches:
                from spark_rapids_tpu.plan.nodes import _empty_table
                out = _empty_table(plan.output_schema())
            else:
                out = HostTable.concat(batches)
        finally:
            TRACER.end(collect_span)
            phases["collectS"] = _time.perf_counter() - t_phase
            # compile accounting AFTER collect: the packed d2h kernels
            # jit during it, and their traces belong to this query
            # (top level only — a nested execute rides the outer's
            # counters, mirroring the reset above)
            if q.exec_depth == 1:
                from spark_rapids_tpu.dispatch import (
                    compile_stats,
                    flush_trace_cache_hits,
                )
                traces, compile_s, pad = compile_stats()
                self.last_compile_ms = round(compile_s * 1000.0, 3)
                self.last_pad_waste_rows = pad
                flush_trace_cache_hits()
        # a fully successful run fills its executable-cache slot (the
        # entry stays checked out until the query envelope releases it)
        if tok is not None and not tok.hit:
            tok.fill(executable, meta)
        return out

    def execute_cpu_only(self, plan: P.PlanNode) -> HostTable:
        """Run fully on the CPU path (the oracle)."""
        return plan.collect_cpu()

    def last_metrics(self) -> str:
        """Per-operator metrics of the most recent execute(), rendered as a
        tree with lore ids (reference: GpuExec metrics + LORE ids shown in
        the Spark UI / explain output)."""
        ex = getattr(self, "_last_executable", None)
        if ex is None:
            return "(no query executed yet)"
        # resolve deferred device row counts (one batched fetch) so
        # numOutputRows is complete in the rendered tree
        from spark_rapids_tpu.obs.spans import finalize_observation
        finalize_observation(ex)
        lines = []

        def walk(e, indent):
            lid = getattr(e, "_lore_id", "?")
            desc = e.describe() if hasattr(e, "describe") else type(e).__name__
            m = getattr(e, "metrics", None)
            mtxt = ""
            if m:
                parts = [f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                         for k, v in sorted(m.items())]
                mtxt = "  [" + ", ".join(parts) + "]"
            lines.append("  " * indent + f"[loreId={lid}] {desc}{mtxt}")
            for c in getattr(e, "children", ()):
                walk(c, indent + 1)
            for attr in ("source", "tpu_exec", "cpu_node", "scan_node"):
                nxt = getattr(e, attr, None)
                if nxt is not None:
                    walk(nxt, indent + 1)

        walk(ex, 0)
        return "\n".join(lines)

    def explain(self, plan: P.PlanNode) -> str:
        return explain_plan(plan, self.conf)
