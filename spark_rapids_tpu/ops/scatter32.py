"""32-bit-safe columnar scatters.

XLA's TPU scatter for 64-bit element types is ~25x slower than for
32-bit (measured on v5e: 120ms vs 5ms for a 1M-row scatter-set — the
emulated wide type serializes; PERF.md). Every row compaction in the
engine (filter, join gather/compact, aggregate output packing, concat)
is a scatter of column payloads, and LONG/DOUBLE columns are the common
case — so every 64-bit payload is split into exact 32-bit halves,
scattered natively, and recombined. f64 splits via
ops/segsum.split_f64_hi_lo (exact on TPU where f64 storage IS an
(f32, f32) pair); i64 splits into sign-preserving hi/lo words.

The CPU backend (virtual-mesh tests) scatters 64-bit natively and skips
the split. Gathers don't need this treatment (64-bit gathers are only
~2x a 32-bit gather)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _split_worthwhile(dtype) -> bool:
    return (jax.default_backend() != "cpu"
            and dtype in (jnp.float64, jnp.int64, jnp.uint64))


def scatter_set(out_len: int, tgt, data, mode: str = "drop"):
    """``zeros(out_len, data.dtype).at[tgt].set(data, mode=mode)`` with
    64-bit payloads scattered as two 32-bit streams. Trailing dims ride
    along (a DECIMAL128 column is a (rows, 2) int64 limb matrix)."""
    shape = (out_len,) + data.shape[1:]
    if not _split_worthwhile(data.dtype):
        return jnp.zeros(shape, data.dtype).at[tgt].set(data, mode=mode)
    from spark_rapids_tpu.ops.limbs import (
        combine_f64,
        combine_i64,
        split_f64_hi_lo,
        split_i64_hi_lo,
    )
    if data.dtype == jnp.float64:
        hi, lo = split_f64_hi_lo(data)
        ohi = jnp.zeros(shape, jnp.float32).at[tgt].set(hi, mode=mode)
        olo = jnp.zeros(shape, jnp.float32).at[tgt].set(lo, mode=mode)
        return combine_f64(ohi, olo)
    hi, lo = split_i64_hi_lo(data)
    ohi = jnp.zeros(shape, jnp.int32).at[tgt].set(hi, mode=mode)
    olo = jnp.zeros(shape, jnp.uint32).at[tgt].set(lo, mode=mode)
    return combine_i64(ohi, olo).astype(data.dtype)


def scatter_pair(out_len: int, tgt, data, validity, mode: str = "drop"):
    """Scatter one column's (data, validity) to ``tgt`` slots."""
    od = scatter_set(out_len, tgt, data, mode=mode)
    ov = jnp.zeros(out_len, jnp.bool_).at[tgt].set(validity, mode=mode)
    return od, ov


def compact_pairs(datas, valids, keep, capacity: int):
    """THE row-compaction dispatch point: compact every column's
    (data, validity) to the kept-row prefix. Returns ([(data,
    validity)...], new_n). The HLO path is the classic per-column
    scatter_pair loop; with the ``compact`` Pallas kernel enabled the
    whole table compacts through ONE i32 gather-map scatter plus one
    fused gather kernel (kernels/compact.py) — bit-identical. Callers
    whose jitted kernels embed this choice fold
    ``kernels.trace_token()`` into their trace cache keys."""
    from spark_rapids_tpu import kernels
    keep_i = keep.astype(jnp.int32)
    new_n = jnp.sum(keep_i)
    pos = jnp.cumsum(keep_i) - 1

    def hlo():
        tgt = jnp.where(keep, pos, capacity)
        return [scatter_pair(capacity, tgt, d, v)
                for d, v in zip(datas, valids)]

    def kern():
        from spark_rapids_tpu.kernels import compact as kcompact
        return kcompact.gather_compact(list(datas), list(valids), keep,
                                       pos, new_n, capacity)

    return kernels.dispatch("compact", kern, hlo), new_n
