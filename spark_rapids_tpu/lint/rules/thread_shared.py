"""RL-THREAD-SHARED — the query service executes queries from a worker
pool, so runtime/, shuffle/ and service/ modules are concurrent by
contract: module-global mutable containers (and class-level singleton
slots) written inside a function must be written under a lock guard
(a ``with <something named *lock*/*cond*>:`` block) or appear in the
sanctioned allowlist with a justification."""

from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make
from spark_rapids_tpu.lint.rules.common import _attr_chain

#: directories whose modules must be thread-safe (the query service's
#: worker pool runs through all three concurrently)
_THREAD_SHARED_DIRS = ("spark_rapids_tpu/runtime/",
                       "spark_rapids_tpu/shuffle/",
                       "spark_rapids_tpu/service/",
                       "spark_rapids_tpu/streaming/")

#: sanctioned unlocked writes: "file:name" -> why the pattern is safe.
#: Additions need a justification a reviewer can check.
_THREAD_SHARED_ALLOWLIST = {
    # speculation's per-attempt context is a contextvar; only the
    # blocklist is shared — and it is lock-guarded after this PR.
}

#: container-mutating method names on dict/list/set/deque
_MUTATING_METHODS = {"append", "extend", "add", "update", "pop",
                     "popitem", "remove", "discard", "clear",
                     "setdefault", "insert", "appendleft", "popleft",
                     "move_to_end"}

_MUTABLE_CTORS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                  "deque", "Counter", "WeakKeyDictionary",
                  "WeakValueDictionary"}


def _is_mutable_container(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set,
                         ast.ListComp, ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        return chain.split(".")[-1] in _MUTABLE_CTORS
    return False


def _is_lock_guard(with_node: ast.With) -> bool:
    for item in with_node.items:
        chain = _attr_chain(item.context_expr).lower()
        if isinstance(item.context_expr, ast.Call):
            chain = _attr_chain(item.context_expr.func).lower()
        if "lock" in chain or "cond" in chain:
            return True
    return False


def _check_thread_shared(rel: str, tree: ast.AST,
                         diags: List[Diagnostic]):
    if not rel.startswith(_THREAD_SHARED_DIRS):
        return
    shared_globals: dict = {}
    class_names = set()
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            class_names.add(node.name)
        target = value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        if target is not None and _is_mutable_container(value):
            shared_globals[target] = node.lineno

    def _flag(node, what, name):
        """``name`` is the allowlist key: the container's global name,
        or the attribute name for class-level singleton slots."""
        if f"{rel}:{name}" in _THREAD_SHARED_ALLOWLIST:
            return
        diags.append(make(
            "RL-THREAD-SHARED", f"{rel}:{node.lineno}",
            f"{what} written outside a lock guard in a module shared "
            "by concurrent query workers; hold a lock (with "
            "<..lock..>:), use threading.local, or allowlist "
            f"{rel}:{name} with a justification"))

    def _root_name(node: ast.AST):
        while isinstance(node, ast.Subscript):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def _is_class_attr_target(node: ast.AST):
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and (node.value.id == "cls"
                     or node.value.id in class_names))

    def walk(node, in_func: bool, guarded: bool, fn_globals):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_func = True
            fn_globals = {n for g in ast.walk(node)
                          if isinstance(g, ast.Global) for n in g.names}
        elif isinstance(node, ast.With) and _is_lock_guard(node):
            guarded = True
        if in_func and not guarded:
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        root = _root_name(t)
                        if root in shared_globals:
                            _flag(node, f"{root}[...]", root)
                    elif isinstance(t, ast.Name) and t.id in fn_globals \
                            and t.id in shared_globals:
                        _flag(node, t.id, t.id)
                    elif _is_class_attr_target(t):
                        _flag(node, f"{_attr_chain(t)} (class attribute)",
                              t.attr)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATING_METHODS:
                root = _root_name(node.func.value)
                if root in shared_globals:
                    _flag(node, f"{root}.{node.func.attr}(...)", root)
        for child in ast.iter_child_nodes(node):
            walk(child, in_func, guarded, fn_globals)

    walk(tree, False, False, set())
