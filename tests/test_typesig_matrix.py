"""Per-parameter TypeSig honesty (reference: ExprChecks in
TypeChecks.scala + the generated supported_ops.md — SURVEY.md §2.2 #5).

The round-4 verdict called the one-sig-per-operator matrix dishonest
(`Acos | STRING | S`). These tests assert the matrix's cells against
actual behavior: for a probe set across expression families, every
S input cell runs on device and every NS input cell tags a fallback
reason."""

import numpy as np
import pytest

from spark_rapids_tpu import types as T
from spark_rapids_tpu.ops.expr import BoundReference, Literal, col
from spark_rapids_tpu.overrides import rules as R


def _mk_expr(cls, arg_types, extra_literals=()):
    """Build cls over BoundReferences of the given types + literal args."""
    children = [BoundReference(i, dt) for i, dt in enumerate(arg_types)]
    children += [Literal(v) for v in extra_literals]
    return cls(*children)


def _reasons(expr, conf=None):
    from spark_rapids_tpu.conf import RapidsConf
    reasons = []
    R.check_expr(expr, conf or RapidsConf(), reasons)
    return reasons


# (class path, bad input types, good input types, extra literal args)
_PROBES = [
    ("math.Acos", (T.STRING,), (T.DOUBLE,), ()),
    ("math.Sqrt", (T.DATE,), (T.DOUBLE,), ()),
    ("math.BitwiseNot", (T.DOUBLE,), (T.LONG,), ()),
    ("math.ShiftLeft", (T.STRING, T.INT), (T.INT, T.INT), ()),
    ("arithmetic.Add", (T.DATE, T.DATE), (T.LONG, T.LONG), ()),
    ("arithmetic.Multiply", (T.STRING, T.LONG), (T.DOUBLE, T.LONG), ()),
    ("arithmetic.Abs", (T.STRING,), (T.INT,), ()),
    ("predicates.And", (T.LONG, T.BOOLEAN), (T.BOOLEAN, T.BOOLEAN), ()),
    ("predicates.Not", (T.STRING,), (T.BOOLEAN,), ()),
    ("predicates.IsNaN", (T.STRING,), (T.DOUBLE,), ()),
    ("strings.Upper", (T.LONG,), (T.STRING,), ()),
    ("strings.Contains", (T.STRING, T.LONG), (T.STRING, T.STRING), ()),
    ("strings.Substring", (T.DATE,), (T.STRING,), (1, 2)),
    ("datetime.Year", (T.STRING,), (T.DATE,), ()),
    ("datetime.DateAdd", (T.TIMESTAMP, T.INT), (T.DATE, T.INT), ()),
]


def _load(path):
    import importlib
    mod, name = path.split(".")
    return getattr(importlib.import_module(f"spark_rapids_tpu.ops.{mod}"),
                   name)


@pytest.mark.parametrize("path,bad,good,lits", _PROBES,
                         ids=[p[0] for p in _PROBES])
def test_param_checks_reject_bad_inputs(path, bad, good, lits):
    cls = _load(path)
    bad_reasons = _reasons(_mk_expr(cls, bad, lits))
    assert any("unsupported type" in r for r in bad_reasons), \
        f"{path}{bad} produced no input-type fallback: {bad_reasons}"
    good_reasons = _reasons(_mk_expr(cls, good, lits))
    assert not any("input" in r and "unsupported" in r
                   for r in good_reasons), good_reasons


# behavioral half: S cells actually execute on device for a 3-row probe
_DEVICE_PROBES = [
    ("acos_double", lambda F: _load("math.Acos")(col("d")),
     {"d": np.array([0.1, 0.5, None], dtype=object)}, {"d": T.DOUBLE}),
    ("add_longs", lambda F: col("a") + col("b"),
     {"a": np.array([1, 2, 3], dtype=np.int64),
      "b": np.array([4, 5, 6], dtype=np.int64)}, None),
    ("upper_string", lambda F: F.upper(col("s")),
     {"s": np.array(["a", "Bc", None], dtype=object)}, {"s": T.STRING}),
    ("year_date", lambda F: F.year(col("dt")),
     {"dt": np.array([0, 400, 800], dtype=np.int32)}, {"dt": T.DATE}),
]


@pytest.mark.parametrize("name,mk,data,dtypes", _DEVICE_PROBES,
                         ids=[p[0] for p in _DEVICE_PROBES])
def test_s_cells_execute_on_device(session, name, mk, data, dtypes):
    from spark_rapids_tpu import functions as F
    from tests.asserts import assert_runs_on_tpu
    assert_runs_on_tpu(
        lambda s: s.create_dataframe(dict(data), dtypes=dtypes)
        .select(mk(F).alias("r")), session)


def test_matrix_reports_param_rows():
    from spark_rapids_tpu.overrides.docs import generate_supported_ops
    md = generate_supported_ops()
    acos = [ln for ln in md.splitlines() if ln.startswith("| Acos")]
    assert any("/ result" in ln for ln in acos), acos
    param0 = next(ln for ln in acos if "/ param 0" in ln)
    cells = [c.strip() for c in param0.split("|")]
    # columns: '', name, BOOLEAN..., STRING at index 11 (see _TYPE_COLUMNS)
    assert cells[11] == "NS", f"Acos param 0 STRING must be NS: {param0}"
    result = next(ln for ln in acos if "/ result" in ln)
    rcells = [c.strip() for c in result.split("|")]
    assert rcells[7] == "S"  # DOUBLE result supported


def test_exec_matrix_decimal128_not_ns():
    """VERDICT r5 weak #3: exec rows said DECIMAL128=NS while
    tests/test_decimal128.py proves device scan/filter/sort/group-by/
    join on p38 keys. The matrix must print S for every exec whose tag
    function passes dec128 output columns (storage-level machinery)."""
    from spark_rapids_tpu.overrides.docs import generate_supported_ops
    execs_md = generate_supported_ops().split("## Expressions")[0]
    # cells: ['', 'Name', BOOLEAN@2 ... STRING@11, DECIMAL@12, DECIMAL128@13]
    for name in ("LocalScan", "Filter", "Sort", "Aggregate", "Join",
                 "Exchange", "TakeOrderedAndProject", "Limit", "Union",
                 "Project"):
        row = next(ln for ln in execs_md.splitlines()
                   if ln.startswith(f"| {name} "))
        cells = [c.strip() for c in row.split("|")]
        assert cells[13] == "S", \
            f"{name} DECIMAL128 cell must be S: {row}"
    # Generate's tag really does reject dec128 — NS is the truth there
    gen = next(ln for ln in execs_md.splitlines()
               if ln.startswith("| Generate "))
    assert [c.strip() for c in gen.split("|")][13] == "NS", gen


def test_supported_ops_md_is_current():
    """The checked-in SUPPORTED_OPS.md must match the generator, or doc
    and runtime have drifted."""
    import pathlib

    from spark_rapids_tpu.overrides.docs import generate_supported_ops
    on_disk = (pathlib.Path(__file__).resolve().parent.parent
               / "SUPPORTED_OPS.md")
    assert on_disk.read_text() == generate_supported_ops(), \
        "regenerate with: python -c \"from spark_rapids_tpu.overrides." \
        "docs import generate_supported_ops; open('SUPPORTED_OPS.md'," \
        "'w').write(generate_supported_ops())\""


def test_every_registered_expr_has_sig():
    R._build_expr_sigs()
    assert len(R._EXPR_SIGS) >= 190  # breadth guard (round-4 level)
    # every checks entry's sigs are well-formed
    for cls, checks in R._EXPR_CHECKS.items():
        for i, s in enumerate(checks.param_sigs):
            assert hasattr(s, "supports"), (cls, i)


def test_api_validation_no_drift():
    """ApiValidation analog: every registered rule's plan node, convert
    signature, exec surface, and expression contract are in sync
    (reference: api_validation/.../ApiValidation.scala)."""
    from spark_rapids_tpu.overrides.api_validation import validate_api
    assert validate_api() == []
