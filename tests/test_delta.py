"""Delta Lake connector tests (reference: delta_lake_*_test.py suites —
write/read roundtrip, time travel, DELETE w/ deletion vectors, UPDATE,
MERGE, OPTIMIZE + Z-ORDER, VACUUM, checkpoints, concurrency)."""

import json
import os

import numpy as np
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu import types as T
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.expr import col, lit


def _data(n=300, seed=0):
    rng = np.random.default_rng(seed)
    return {"id": np.arange(n, dtype=np.int64),
            "k": rng.integers(0, 5, n).astype(np.int64),
            "v": rng.standard_normal(n),
            "s": np.array([f"s{int(x)}" for x in
                           rng.integers(0, 50, n)], dtype=object)}


# -- roaring bitmap codec ----------------------------------------------------

def test_roaring_roundtrip_small_and_dense():
    from spark_rapids_tpu.delta.roaring import deserialize_dv, serialize_dv
    for idxs in ([0, 5, 17, 100000],
                 list(range(0, 70000)),               # bitmap container
                 [2**32 + 7, 2**33, 5],               # multiple high words
                 []):
        arr = np.array(sorted(set(idxs)), dtype=np.int64)
        got = deserialize_dv(serialize_dv(arr))
        assert got.tolist() == arr.tolist()


def test_roaring_run_container_read():
    """Write the run-container flavor by hand and read it back."""
    import struct
    from spark_rapids_tpu.delta.roaring import deserialize_bitmap32
    # one container (key 0) with runs [10..20], [50..52]
    cookie = ((1 - 1) << 16) | 12346
    buf = struct.pack("<I", cookie)
    buf += bytes([0b1])                      # run flag for container 0
    buf += struct.pack("<HH", 0, 14 - 1)     # key, card-1 (14 values)
    buf += struct.pack("<H", 2)              # n_runs
    buf += struct.pack("<HH", 10, 10)        # 10..20
    buf += struct.pack("<HH", 50, 2)         # 50..52
    vals, _used = deserialize_bitmap32(buf)
    assert vals.tolist() == list(range(10, 21)) + [50, 51, 52]


# -- write / read roundtrip --------------------------------------------------

def test_create_append_read(tmp_path, session, cpu_session):
    path = str(tmp_path / "t1")
    df = session.create_dataframe(_data(300, seed=1))
    v0 = df.write_delta(path)
    assert v0 == 0
    v1 = session.create_dataframe(_data(200, seed=2)).write_delta(
        path, mode="append")
    assert v1 == 1

    got = session.read_delta(path)
    assert got.count() == 500
    # oracle: TPU vs CPU session read identical
    trows = sorted(session.read_delta(path).collect())
    crows = sorted(cpu_session.read_delta(path).collect())
    assert trows == crows

    # mode=error rejects
    with pytest.raises(ColumnarProcessingError, match="already exists"):
        df.write_delta(path)


def test_time_travel_and_overwrite(tmp_path, session):
    path = str(tmp_path / "t2")
    session.create_dataframe(_data(100, seed=3)).write_delta(path)
    session.create_dataframe(_data(50, seed=4)).write_delta(
        path, mode="append")
    session.create_dataframe(_data(20, seed=5)).write_delta(
        path, mode="overwrite")
    assert session.read_delta(path).count() == 20
    assert session.read_delta(path, version_as_of=0).count() == 100
    assert session.read_delta(path, version_as_of=1).count() == 150


def test_partitioned_write_and_read(tmp_path, session):
    path = str(tmp_path / "t3")
    session.create_dataframe(_data(400, seed=6)).write_delta(
        path, partition_by=["k"])
    t = session.read_delta(path)
    assert t.count() == 400
    assert sorted(set(r[1] for r in t.select("id", "k").collect())) == \
        [0, 1, 2, 3, 4]
    # partition pruning data lives in the log, not dirs — but dirs are
    # hive-style for interop
    assert any("k=" in d for d in os.listdir(path) if not
               d.startswith("_"))
    # filter on partition column
    assert t.filter(col("k") == 2).count() == \
        sum(1 for x in _data(400, seed=6)["k"] if x == 2)


def test_stats_written(tmp_path, session):
    from spark_rapids_tpu.delta import DeltaLog
    path = str(tmp_path / "t4")
    session.create_dataframe(_data(100, seed=7)).write_delta(path)
    snap = DeltaLog(path).snapshot()
    stats = json.loads(snap.files[0].stats)
    assert stats["numRecords"] == 100
    assert stats["minValues"]["id"] == 0
    assert stats["maxValues"]["id"] == 99


# -- DELETE ------------------------------------------------------------------

def test_delete_with_deletion_vectors(tmp_path, session):
    from spark_rapids_tpu.delta import DeltaLog
    path = str(tmp_path / "t5")
    session.create_dataframe(_data(300, seed=8)).write_delta(path)
    dt = session.delta_table(path)
    res = dt.delete(col("id") < 50)
    assert res["num_affected_rows"] == 50
    assert session.read_delta(path).count() == 250
    # partial delete used a DV, not a rewrite
    snap = DeltaLog(path).snapshot()
    assert len(snap.files) == 1
    assert snap.files[0].deletion_vector is not None
    assert snap.files[0].deletion_vector["cardinality"] == 50

    # second delete merges into the DV
    res2 = dt.delete(col("id") < 80)
    assert res2["num_affected_rows"] == 30
    assert session.read_delta(path).count() == 220
    # idempotent: deleting the same range again affects nothing
    assert dt.delete(col("id") < 80)["num_affected_rows"] == 0

    # full delete removes the file
    dt.delete()
    assert session.read_delta(path).count() == 0


def test_delete_time_travel_preserves_old_versions(tmp_path, session):
    path = str(tmp_path / "t6")
    session.create_dataframe(_data(100, seed=9)).write_delta(path)
    session.delta_table(path).delete(col("id") >= 90)
    assert session.read_delta(path).count() == 90
    assert session.read_delta(path, version_as_of=0).count() == 100


# -- UPDATE ------------------------------------------------------------------

def test_update(tmp_path, session):
    path = str(tmp_path / "t7")
    session.create_dataframe(_data(200, seed=10)).write_delta(path)
    dt = session.delta_table(path)
    res = dt.update(col("id") < 10, {"v": lit(99.5), "s": lit("updated")})
    assert res["num_affected_rows"] == 10
    rows = {r[0]: (r[2], r[3]) for r in
            session.read_delta(path).select("id", "k", "v", "s").collect()}
    for i in range(10):
        assert rows[i] == (99.5, "updated")
    assert rows[50] != (99.5, "updated")
    assert session.read_delta(path).count() == 200


def test_update_expression_over_columns(tmp_path, session):
    path = str(tmp_path / "t8")
    session.create_dataframe(_data(100, seed=11)).write_delta(path)
    session.delta_table(path).update(None, {"v": col("v") * lit(2.0)})
    orig = _data(100, seed=11)["v"]
    got = {r[0]: r[1] for r in
           session.read_delta(path).select("id", "v").collect()}
    for i in range(100):
        assert abs(got[i] - orig[i] * 2) < 1e-12


# -- MERGE -------------------------------------------------------------------

def test_merge_update_insert(tmp_path, session):
    path = str(tmp_path / "t9")
    session.create_dataframe(
        {"id": np.arange(10, dtype=np.int64),
         "v": np.zeros(10)}).write_delta(path)
    source = session.create_dataframe(
        {"id": np.array([5, 6, 20, 21], dtype=np.int64),
         "v": np.array([55.0, 66.0, 2.0, 2.1])})
    res = (session.delta_table(path)
           .merge(source, on=["id"])
           .when_matched_update(set={"v": "v"})
           .when_not_matched_insert()
           .execute())
    assert res["num_matched_rows"] == 2
    assert res["num_inserted_rows"] == 2
    rows = dict(session.read_delta(path).select("id", "v").collect())
    assert rows[5] == 55.0 and rows[6] == 66.0
    assert rows[20] == 2.0 and rows[21] == 2.1
    assert rows[0] == 0.0
    assert len(rows) == 12


def test_merge_delete(tmp_path, session):
    path = str(tmp_path / "t10")
    session.create_dataframe(
        {"id": np.arange(10, dtype=np.int64),
         "v": np.ones(10)}).write_delta(path)
    source = session.create_dataframe(
        {"id": np.array([3, 4], dtype=np.int64),
         "v": np.zeros(2)})
    res = (session.delta_table(path).merge(source, on=["id"])
           .when_matched_delete().execute())
    assert res["num_deleted_rows"] == 2
    ids = sorted(r[0] for r in session.read_delta(path)
                 .select("id").collect())
    assert ids == [0, 1, 2, 5, 6, 7, 8, 9]


# -- OPTIMIZE / ZORDER -------------------------------------------------------

def test_optimize_compacts_small_files(tmp_path, session):
    from spark_rapids_tpu.delta import DeltaLog
    path = str(tmp_path / "t11")
    for i in range(4):
        session.create_dataframe(_data(50, seed=20 + i)).write_delta(
            path, mode="append" if i else "error")
    assert len(DeltaLog(path).snapshot().files) == 4
    res = session.delta_table(path).optimize()
    assert res["files_removed"] == 4 and res["files_added"] == 1
    assert len(DeltaLog(path).snapshot().files) == 1
    assert session.read_delta(path).count() == 200


def test_zorder_clusters(tmp_path, session):
    from spark_rapids_tpu.delta import DeltaLog
    path = str(tmp_path / "t12")
    rng = np.random.default_rng(0)
    session.create_dataframe(
        {"x": rng.integers(0, 100, 1000).astype(np.int64),
         "y": rng.integers(0, 100, 1000).astype(np.int64)}).write_delta(path)
    session.delta_table(path).optimize(zorder_by=["x", "y"])
    assert session.read_delta(path).count() == 1000
    # z-order property: consecutive rows are close in BOTH x and y on
    # average (vs random order). Check mean successive |dx|+|dy| shrinks.
    rows = session.read_delta(path).select("x", "y").collect()
    xs = np.array([r[0] for r in rows], dtype=float)
    ys = np.array([r[1] for r in rows], dtype=float)
    d = np.abs(np.diff(xs)) + np.abs(np.diff(ys))
    assert d.mean() < 25  # random order averages ~66 for uniform [0,100)


def test_zorder_key_interleaving_exact():
    from spark_rapids_tpu.columnar import HostTable
    from spark_rapids_tpu.delta.zorder import zorder_key_host
    t = HostTable.from_pydict({
        "a": np.array([0, 0, 3, 3], dtype=np.int64),
        "b": np.array([0, 3, 0, 3], dtype=np.int64)})
    z = zorder_key_host(t, ["a", "b"])
    # (0,0) < (0,3) and (3,0) interleave below (3,3)
    assert z[0] == min(z) and z[3] == max(z)


# -- VACUUM / history / checkpoint -------------------------------------------

def test_vacuum_removes_orphans(tmp_path, session):
    path = str(tmp_path / "t13")
    session.create_dataframe(_data(100, seed=30)).write_delta(path)
    session.create_dataframe(_data(100, seed=31)).write_delta(
        path, mode="overwrite")
    res = session.delta_table(path).vacuum()
    assert res["files_deleted"] >= 1
    assert session.read_delta(path).count() == 100
    # time travel to v0 is now broken (files gone) — that's vacuum's deal
    with pytest.raises(Exception):
        session.read_delta(path, version_as_of=0).collect()


def test_history(tmp_path, session):
    path = str(tmp_path / "t14")
    session.create_dataframe(_data(10, seed=32)).write_delta(path)
    session.delta_table(path).delete(col("id") < 5)
    h = session.delta_table(path).history()
    assert [e["version"] for e in h] == [1, 0]
    assert h[0]["operation"] == "DELETE"


def test_checkpoint_replay(tmp_path, session):
    from spark_rapids_tpu.delta import DeltaLog
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.delta.checkpointInterval": "4"})
    path = str(tmp_path / "t15")
    for i in range(6):
        s.create_dataframe(_data(10, seed=40 + i)).write_delta(
            path, mode="append" if i else "error")
    # checkpoint exists at v4
    assert os.path.exists(os.path.join(
        path, "_delta_log", f"{4:020d}.checkpoint.parquet"))
    log = DeltaLog(path)
    assert log._last_checkpoint()["version"] == 4
    snap = log.snapshot()
    assert len(snap.files) == 6
    assert s.read_delta(path).count() == 60
    # replay from checkpoint equals full replay
    full = DeltaLog(path)
    full_snap = full.snapshot()
    assert sorted(a.path for a in full_snap.files) == \
        sorted(a.path for a in snap.files)


def test_concurrent_commit_conflict(tmp_path, session):
    from spark_rapids_tpu.delta import DeltaLog
    from spark_rapids_tpu.delta.log import DeltaConcurrentModificationException
    path = str(tmp_path / "t16")
    session.create_dataframe(_data(10, seed=50)).write_delta(path)
    log = DeltaLog(path)
    # both writers target version 1; the second direct commit must fail
    log.commit([], 1, "TEST")
    with pytest.raises(DeltaConcurrentModificationException):
        log.commit([], 1, "TEST")
    # the transaction layer retries past the conflict
    v2 = session.create_dataframe(_data(5, seed=51)).write_delta(
        path, mode="append")
    assert v2 == 2


def test_delta_scan_through_engine_ops(tmp_path, session, cpu_session):
    path = str(tmp_path / "t17")
    session.create_dataframe(_data(500, seed=60)).write_delta(path)

    def q(s):
        return (s.read_delta(path)
                .filter(col("v") > 0)
                .group_by("k").agg(F.count("id").alias("c"),
                                   F.sum("v").alias("sv")))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[1] == w[1]
        assert abs(g[2] - w[2]) <= 1e-6 * max(1.0, abs(w[2]))


def test_merge_duplicate_source_keys_rejected(tmp_path, session):
    path = str(tmp_path / "t18")
    session.create_dataframe(
        {"id": np.arange(5, dtype=np.int64),
         "v": np.zeros(5)}).write_delta(path)
    dup = session.create_dataframe(
        {"id": np.array([1, 1], dtype=np.int64),
         "v": np.array([7.0, 8.0])})
    with pytest.raises(ColumnarProcessingError, match="multiple rows"):
        (session.delta_table(path).merge(dup, on=["id"])
         .when_matched_update(set={"v": "v"}).execute())


def test_overwrite_schema_mismatch_rejected(tmp_path, session):
    path = str(tmp_path / "t19")
    session.create_dataframe(_data(10, seed=70)).write_delta(path)
    other = session.create_dataframe({"a": np.arange(3, dtype=np.int64)})
    with pytest.raises(ColumnarProcessingError, match="schema mismatch"):
        other.write_delta(path, mode="overwrite")
    # mode=ignore is a no-op on existing tables
    v = other.write_delta(path, mode="ignore")
    assert v == 0 and session.read_delta(path).count() == 10


def test_partition_only_projection(tmp_path, session):
    path = str(tmp_path / "t20")
    session.create_dataframe(_data(100, seed=80)).write_delta(
        path, partition_by=["k"])
    t = session.read_delta(path, columns=["k"]).collect_table()
    assert t.num_rows == 100 and list(t.names) == ["k"]


def test_append_partitioning_mismatch_rejected(tmp_path, session):
    path = str(tmp_path / "t21")
    session.create_dataframe(_data(20, seed=81)).write_delta(
        path, partition_by=["k"])
    with pytest.raises(ColumnarProcessingError, match="partitioning"):
        session.create_dataframe(_data(20, seed=82)).write_delta(
            path, mode="append")
    # matching partition_by appends fine
    session.create_dataframe(_data(20, seed=83)).write_delta(
        path, mode="append", partition_by=["k"])
    assert session.read_delta(path).count() == 40


def test_merge_null_keys_never_match(tmp_path, session):
    path = str(tmp_path / "t22")
    import pandas as pd
    pdf = pd.DataFrame({"id": pd.array([0, 1, None], dtype="Int64"),
                        "v": [1.0, 2.0, 3.0]})
    session.create_dataframe(pdf).write_delta(path)
    src = session.create_dataframe(
        {"id": np.array([0], dtype=np.int64), "v": np.array([99.0])})
    res = (session.delta_table(path).merge(src, on=["id"])
           .when_matched_update(set={"v": "v"}).execute())
    assert res["num_matched_rows"] == 1  # NULL-keyed row did NOT match id=0
    rows = session.read_delta(path).select("id", "v").collect()
    by_id = {r[0]: r[1] for r in rows}
    assert by_id[0] == 99.0 and by_id[1] == 2.0 and by_id[None] == 3.0


def test_merge_update_and_delete_combination_rejected(tmp_path, session):
    path = str(tmp_path / "t23")
    session.create_dataframe(_data(5, seed=84)).write_delta(path)
    src = session.create_dataframe({"id": np.array([1], dtype=np.int64)})
    mb = session.delta_table(path).merge(src, on=["id"])
    mb.when_matched_update(set={})
    with pytest.raises(ColumnarProcessingError, match="cannot combine"):
        mb.when_matched_delete()


# -- round-4 ADVICE regressions: checkpoint schema + DV framing --------------

def test_checkpoint_spec_schema_roundtrip(tmp_path, session):
    """Checkpoints are written in the spec's nested action schema and
    snapshot replay from the checkpoint equals a full log replay."""
    import pyarrow.parquet as pq
    from spark_rapids_tpu.delta.log import DeltaLog
    path = str(tmp_path / "tcp")
    s2 = type(session)({"spark.rapids.delta.checkpointInterval": "3"})
    for i in range(5):
        s2.create_dataframe(_data(40, seed=20 + i)).write_delta(
            path, mode="append" if i else "error")
    log = DeltaLog(path)
    cp = log._last_checkpoint()
    assert cp is not None and cp["version"] >= 2
    t = pq.read_table(os.path.join(
        path, "_delta_log", f"{cp['version']:020d}.checkpoint.parquet"))
    assert {"protocol", "metaData", "add"} <= set(t.schema.names)
    # from-checkpoint replay == full replay (delete the pointer to force)
    snap_cp = log.snapshot()
    os.remove(os.path.join(path, "_delta_log", "_last_checkpoint"))
    snap_full = DeltaLog(path).snapshot()
    assert sorted(a.path for a in snap_cp.files) == \
        sorted(a.path for a in snap_full.files)
    assert snap_cp.metadata.schema_json == snap_full.metadata.schema_json


def test_unrecognized_checkpoint_falls_back_to_full_replay(tmp_path, session):
    """A schema-mismatched checkpoint must NOT silently drop
    pre-checkpoint files (ADVICE r2: delta/log.py)."""
    import json as _json
    import pyarrow as pa
    import pyarrow.parquet as pq
    from spark_rapids_tpu.delta.log import DeltaLog
    path = str(tmp_path / "tbad")
    for i in range(4):
        session.create_dataframe(_data(30, seed=30 + i)).write_delta(
            path, mode="append" if i else "error")
    log = DeltaLog(path)
    full = sorted(a.path for a in log.snapshot().files)
    # plant a checkpoint whose schema we don't recognize
    bogus = pa.Table.from_pylist([{"txn": "x"}])
    pq.write_table(bogus, os.path.join(
        path, "_delta_log", f"{2:020d}.checkpoint.parquet"))
    with open(os.path.join(path, "_delta_log", "_last_checkpoint"), "w") as f:
        _json.dump({"version": 2, "size": 1}, f)
    got = sorted(a.path for a in DeltaLog(path).snapshot().files)
    assert got == full  # fell back to full replay, nothing dropped


def test_dv_file_spec_framing(tmp_path, session):
    """DV files carry version byte + size prefix + CRC; descriptors use
    'u' storage; 'p' absolute and 'i' inline read paths work."""
    import base64
    import zlib
    from spark_rapids_tpu.delta.table import read_dv, write_dv_file
    from spark_rapids_tpu.delta.roaring import serialize_dv
    tp = str(tmp_path)
    idx = np.array([1, 5, 7, 100000], dtype=np.int64)
    desc = write_dv_file(tp, idx)
    assert desc["storageType"] == "u" and desc["offset"] == 1
    # on-disk framing
    from spark_rapids_tpu.delta.table import _dv_relative_path
    p = os.path.join(tp, _dv_relative_path(desc["pathOrInlineDv"]))
    raw = open(p, "rb").read()
    assert raw[0] == 1
    size = int.from_bytes(raw[1:5], "big")
    blob = raw[5:5 + size]
    assert int.from_bytes(raw[5 + size:9 + size], "big") == zlib.crc32(blob)
    assert read_dv(tp, desc).tolist() == idx.tolist()
    # corrupted blob -> checksum error
    bad = bytearray(raw)
    bad[6] ^= 0xFF
    open(p, "wb").write(bytes(bad))
    with pytest.raises(ColumnarProcessingError):
        read_dv(tp, desc)
    open(p, "wb").write(raw)
    # 'i' inline
    blob2 = serialize_dv(idx)
    inline = {"storageType": "i",
              "pathOrInlineDv": base64.b85encode(blob2).decode(),
              "offset": 0, "sizeInBytes": len(blob2), "cardinality": 4}
    assert read_dv(tp, inline).tolist() == idx.tolist()
    # 'p' absolute
    pdesc = {"storageType": "p", "pathOrInlineDv": p, "offset": 1,
             "sizeInBytes": size, "cardinality": 4}
    assert read_dv(tp, pdesc).tolist() == idx.tolist()


def test_delete_dv_roundtrip_with_new_framing(tmp_path, session, cpu_session):
    path = str(tmp_path / "tdv2")
    session.create_dataframe(_data(200, seed=40)).write_delta(path)
    session.delta_table(path).delete(col("id") < lit(60))
    got = sorted(session.read_delta(path).collect(), key=repr)
    assert len(got) == 140
    assert all(r[0] >= 60 for r in got)


# -- low-shuffle MERGE (GpuLowShuffleMergeCommand analog; VERDICT r4 #8) -----

def _two_file_table(s, tmp_path):
    import numpy as np
    path = str(tmp_path / "lsm")
    s.create_dataframe({"k": np.arange(0, 50, dtype=np.int64),
                        "v": np.arange(0, 50, dtype=np.int64)}) \
        .write_delta(path)
    s.create_dataframe({"k": np.arange(50, 100, dtype=np.int64),
                        "v": np.arange(50, 100, dtype=np.int64)}) \
        .write_delta(path, mode="append")
    return path


def test_low_shuffle_merge_only_touches_matched_rows(session, tmp_path):
    """MERGE touching keys only in file 2: file 1's AddFile survives
    untouched; file 2 keeps its PATH with a deletion vector plus a small
    file holding just the updated rows."""
    import numpy as np
    from spark_rapids_tpu.delta.log import DeltaLog

    path = _two_file_table(session, tmp_path)
    before = {a.path for a in DeltaLog(path).snapshot().files}
    src = session.create_dataframe(
        {"k": np.array([60, 70], dtype=np.int64),
         "nv": np.array([-1, -2], dtype=np.int64)})
    stats = (session.delta_table(path).merge(src, on=["k"])
             .when_matched_update(set={"v": "nv"}).execute())
    assert stats["num_matched_rows"] == 2
    assert stats["low_shuffle"] is True
    assert stats["num_rewritten_files"] == 0
    assert stats["num_dv_files"] == 1

    snap = DeltaLog(path).snapshot()
    after = {a.path for a in snap.files}
    # both ORIGINAL paths survive (file 2 now carries a DV), plus one
    # small file with the 2 updated rows
    assert before <= after
    assert len(after) == 3
    dv_adds = [a for a in snap.files if a.deletion_vector]
    assert len(dv_adds) == 1 and dv_adds[0].path in before

    rows = dict(session.read_delta(path).collect())
    want = {k: (-1 if k == 60 else -2 if k == 70 else k)
            for k in range(100)}
    assert rows == want


def test_low_shuffle_merge_delete_writes_no_data_file(session, tmp_path):
    import numpy as np
    from spark_rapids_tpu.delta.log import DeltaLog

    path = _two_file_table(session, tmp_path)
    before = {a.path for a in DeltaLog(path).snapshot().files}
    src = session.create_dataframe({"k": np.array([10, 99],
                                                  dtype=np.int64)})
    stats = (session.delta_table(path).merge(src, on=["k"])
             .when_matched_delete().execute())
    assert stats["num_deleted_rows"] == 2 and stats["num_dv_files"] == 2
    after = {a.path for a in DeltaLog(path).snapshot().files}
    assert after == before  # DVs only — no new files at all
    got = sorted(r[0] for r in session.read_delta(path).collect())
    assert got == [k for k in range(100) if k not in (10, 99)]


def test_full_rewrite_merge_when_disabled(tmp_path, session):
    import numpy as np
    from spark_rapids_tpu.delta.log import DeltaLog
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession({"spark.rapids.sql.delta.lowShuffleMerge.enabled":
                    "false"})
    path = _two_file_table(s, tmp_path)
    src = s.create_dataframe({"k": np.array([60], dtype=np.int64),
                              "nv": np.array([-1], dtype=np.int64)})
    stats = (s.delta_table(path).merge(src, on=["k"])
             .when_matched_update(set={"v": "nv"}).execute())
    assert stats["low_shuffle"] is False
    assert stats["num_rewritten_files"] == 1
    rows = dict(s.read_delta(path).collect())
    assert rows[60] == -1 and rows[0] == 0 and len(rows) == 100


# -- schema evolution (mergeSchema; VERDICT r4 #8) ---------------------------

def test_append_with_added_column_merge_schema(session, tmp_path):
    import numpy as np
    from spark_rapids_tpu.delta.log import DeltaLog

    path = str(tmp_path / "evo")
    session.create_dataframe(
        {"k": np.arange(5, dtype=np.int64)}).write_delta(path)
    # without the flag: clear error
    df2 = session.create_dataframe(
        {"k": np.arange(5, 8, dtype=np.int64),
         "extra": np.array([1.5, 2.5, 3.5])})
    import pytest as _pt
    from spark_rapids_tpu.errors import ColumnarProcessingError
    with _pt.raises(ColumnarProcessingError, match="merge_schema"):
        df2.write_delta(path, mode="append")

    v = df2.write_delta(path, mode="append", merge_schema=True)
    snap = DeltaLog(path).snapshot()
    # log-recorded schema change
    assert [n for n, _ in snap.schema] == ["k", "extra"]
    got = sorted(session.read_delta(path).collect(), key=repr)
    # old files null-fill the added column
    assert (0, None) in got and any(r == (5, 1.5) for r in got)
    assert len(got) == 8
    assert v == 1  # create=0, evolving append=1


def test_merge_schema_type_conflict_raises(session, tmp_path):
    import numpy as np
    import pytest as _pt
    from spark_rapids_tpu.errors import ColumnarProcessingError

    path = str(tmp_path / "evo2")
    session.create_dataframe(
        {"k": np.arange(3, dtype=np.int64)}).write_delta(path)
    bad = session.create_dataframe({"k": np.array([1.0, 2.0])})
    with _pt.raises(ColumnarProcessingError, match="cannot change"):
        bad.write_delta(path, mode="append", merge_schema=True)


def test_low_shuffle_insert_only_keeps_matched_rows(session, tmp_path):
    """Insert-only MERGE must not touch matched target rows (review fix:
    the DV path was killing them)."""
    import numpy as np
    path = str(tmp_path / "io")
    session.create_dataframe({"k": np.arange(5, dtype=np.int64),
                              "v": np.arange(5, dtype=np.int64)}) \
        .write_delta(path)
    src = session.create_dataframe(
        {"k": np.array([3, 7], dtype=np.int64),
         "v": np.array([30, 70], dtype=np.int64)})
    stats = (session.delta_table(path).merge(src, on=["k"])
             .when_not_matched_insert().execute())
    assert stats["num_inserted_rows"] == 1
    rows = dict(session.read_delta(path).collect())
    assert rows == {0: 0, 1: 1, 2: 2, 3: 3, 4: 4, 7: 70}


def test_low_shuffle_update_casts_source_dtype(session, tmp_path):
    """Update with a float source column into an int target casts like
    the full-rewrite path (review fix)."""
    import numpy as np
    path = str(tmp_path / "cast")
    session.create_dataframe({"k": np.arange(4, dtype=np.int64),
                              "v": np.arange(4, dtype=np.int64)}) \
        .write_delta(path)
    src = session.create_dataframe(
        {"k": np.array([2], dtype=np.int64), "nv": np.array([7.5])})
    (session.delta_table(path).merge(src, on=["k"])
     .when_matched_update(set={"v": "nv"}).execute())
    rows = dict(session.read_delta(path).collect())
    assert rows[2] == 7 and rows[0] == 0


def test_merge_update_after_schema_evolution(session, tmp_path):
    """MERGE updating the EVOLVED column of a pre-evolution file:
    _read_physical null-fills (review fix — used to crash)."""
    import numpy as np
    path = str(tmp_path / "evo3")
    session.create_dataframe({"k": np.arange(3, dtype=np.int64)}) \
        .write_delta(path)
    session.create_dataframe(
        {"k": np.array([10], dtype=np.int64),
         "extra": np.array([5.0])}) \
        .write_delta(path, mode="append", merge_schema=True)
    src = session.create_dataframe(
        {"k": np.array([1], dtype=np.int64), "ne": np.array([9.5])})
    (session.delta_table(path).merge(src, on=["k"])
     .when_matched_update(set={"extra": "ne"}).execute())
    rows = dict(session.read_delta(path).collect())
    assert rows[1] == 9.5 and rows[0] is None and rows[10] == 5.0


def test_merge_schema_commit_does_not_blind_retry(session, tmp_path):
    """A concurrent winner between snapshot and commit surfaces as a
    conflict for mergeSchema appends instead of silently reverting the
    winner's schema (review fix)."""
    import numpy as np
    import pytest as _pt
    from spark_rapids_tpu.delta.log import (
        DeltaConcurrentModificationException,
        DeltaLog,
        Metadata,
    )
    from spark_rapids_tpu.delta.table import (
        OptimisticTransaction,
        schema_to_json,
    )
    from spark_rapids_tpu import types as T

    path = str(tmp_path / "conc")
    session.create_dataframe({"k": np.arange(3, dtype=np.int64)}) \
        .write_delta(path)
    log = DeltaLog(path)
    snap = log.snapshot()
    txn = OptimisticTransaction(log, session.conf,
                                read_version=snap.version)
    txn.stage(Metadata(schema_to_json(list(snap.schema) + [("x", T.LONG)]),
                       [], table_id=snap.metadata.table_id))
    # concurrent winner commits first
    session.create_dataframe({"k": np.array([9], dtype=np.int64)}) \
        .write_delta(path, mode="append")
    with _pt.raises(DeltaConcurrentModificationException):
        txn.commit("WRITE (append)")
