"""TPU equi-join (reference: GpuShuffledHashJoinExec / GpuBroadcastHashJoin /
GpuHashJoin.scala gather-map machinery + JoinGatherer — SURVEY.md §2.3).

TPU-first design: hash tables are pointer-chasing and hostile to the VPU, so
the join is SORT/SEARCH based with fully static shapes:

  1. evaluate key expressions on both sides (fused projections);
  2. dense-rank both sides' keys into ONE shared integer code space
     (device ``lax.sort`` + adjacent-change cumsum — the XLA analog of
     cuDF's build-side hash table); string keys are first remapped into the
     union dictionary on host (dictionary-size work, not row-size);
  3. sort the build side's codes, ``searchsorted`` each probe code for its
     match range [lo, hi) — the GatherMap analog;
  4. expand ranges into (left_idx, right_idx) gather maps with a cumsum
     offset trick at a bucketed static output capacity (JoinGatherer
     analog — one host sync per join for the output size);
  5. gather both sides' columns; outer rows gather index -1 -> null row.

Join types: inner, left, right (as swapped left), full, leftsemi, leftanti
(compaction, no gather maps), cross. Residual non-equi conditions apply as a
post-filter for inner/cross; outer-with-condition falls back (tagged).
"""

from __future__ import annotations

import contextvars
from typing import List, Optional, Sequence, Tuple

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import DeviceColumn, DeviceTable, bucket_for
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.execs.base import TpuExec
from spark_rapids_tpu.ops.expr import Expression, compile_project
from spark_rapids_tpu.ops.ordering import (
    comparable_operands,
    operands_equal_adjacent,
)

INT32_MAX = np.iinfo(np.int32).max

#: (data, validity) pair for key columns
DevVal = Tuple[jax.Array, jax.Array]

#: spark.rapids.tpu.join.directTableMultiplier, set per-query by the
#: session (execs have no conf handle — same pattern as MAX_RETRIES_VAR)
DIRECT_TABLE_MULT = contextvars.ContextVar("rapids_direct_join_mult",
                                           default=4)


def _dense_rank_ops(ops, valid):
    """Dense ranks [0, nvalid) over valid entries; -1 for invalid. One
    multi-operand native-width sort (ops/ordering.lex_sort — no emulated
    64-bit compares) + adjacent-change cumsum + scatter-back. Output ranks
    are i32: row counts never exceed 2^31 (power-of-two row buckets)."""
    from spark_rapids_tpu.ops.ordering import lex_sort
    n = ops[0].shape[0]
    zops = [jnp.where(valid, o, jnp.zeros_like(o)) for o in ops]
    res = lex_sort([(~valid).astype(jnp.int32)] + zops,
                   jnp.arange(n, dtype=jnp.int32))
    perm = res[-1]
    s_valid = res[0] == 0
    first = jnp.arange(n) == 0
    changed = first | ~operands_equal_adjacent(res[1:-1])
    new_grp = changed & s_valid
    rank_sorted = jnp.cumsum(new_grp.astype(jnp.int32)) - 1
    rank_sorted = jnp.where(s_valid, rank_sorted, -1)
    return jnp.zeros(n, dtype=jnp.int32).at[perm].set(rank_sorted)


class JoinKernel:
    """Jitted phases of one join shape; caches traces per capacity tuple.

    Instances are pooled process-wide by ``n_keys`` (``JoinKernel.get``):
    every trace depends only on n_keys + capacities + dtypes, so all joins
    of the same key arity share one compiled set across queries."""

    _instances = {}

    @classmethod
    def get(cls, n_keys: int) -> "JoinKernel":
        k = cls._instances.get(n_keys)
        if k is None:
            k = cls(n_keys)
            cls._instances[n_keys] = k
        return k

    def __init__(self, n_keys: int):
        self.n_keys = n_keys
        self._probe_traces = {}
        self._gather_traces = {}
        self._aux_traces = {}  # _right_matched/_compact/_cross helper jits

    # -- phase A: shared code space + probe ranges --------------------------
    def probe(self, lkeys: List[DevVal], rkeys, nl_dev, nr_dev,
              cap_l: int, cap_r: int, live_l_mask=None):
        from spark_rapids_tpu import kernels
        tkey = (cap_l, cap_r, live_l_mask is not None,
                kernels.trace_token(),
                tuple(str(k[0].dtype) for k in lkeys),
                tuple(str(k[0].dtype) for k in rkeys))
        fn = self._probe_traces.get(tkey)
        if fn is None:
            fn = tpu_jit(self._build_probe(cap_l, cap_r))
            self._probe_traces[tkey] = fn
        return fn(tuple(lkeys), tuple(rkeys), nl_dev, nr_dev, live_l_mask)

    def _build_probe(self, cap_l: int, cap_r: int):
        n_keys = self.n_keys

        def probe(lkeys, rkeys, nl, nr, live_l_mask):
            n = cap_l + cap_r
            if live_l_mask is not None:  # masked probe batch
                live_l = live_l_mask
            else:
                live_l = jnp.arange(cap_l, dtype=jnp.int32) < nl
            live_r = jnp.arange(cap_r, dtype=jnp.int32) < nr

            valid_l = live_l
            valid_r = live_r
            for (ld, lv), (rd, rv) in zip(lkeys, rkeys):
                valid_l = valid_l & lv
                valid_r = valid_r & rv

            allvalid = jnp.concatenate([valid_l, valid_r])
            combined = None
            for (ld, lv), (rd, rv) in zip(lkeys, rkeys):
                ops_l = comparable_operands(ld)
                ops_r = comparable_operands(rd)
                allops = [jnp.concatenate([a, b])
                          for a, b in zip(ops_l, ops_r)]
                rank = _dense_rank_ops(allops, allvalid)
                if combined is None:
                    combined = rank
                else:
                    # re-densify the (combined, rank) pair — two i32 keys,
                    # no overflow-prone combined*n arithmetic
                    combined = _dense_rank_ops(
                        [combined, rank], allvalid & (rank >= 0))
            l_codes = combined[:cap_l]
            r_codes = combined[cap_l:]
            l_codes = jnp.where(valid_l, l_codes, -1)

            # sort build-side codes; invalid/dead rows park at +inf
            from spark_rapids_tpu.ops.ordering import lex_sort
            r_sortable = jnp.where(valid_r, r_codes, INT32_MAX)
            _, rs_perm = lex_sort([r_sortable],
                                  jnp.arange(cap_r, dtype=jnp.int32))

            # codes are DENSE ranks < cap_l + cap_r, so per-code build
            # counts + an exclusive prefix give each probe code's sorted
            # range with two GATHERS — no log(n) searchsorted passes
            n_codes = cap_l + cap_r
            park = jnp.where(valid_r, r_codes, n_codes)
            bc = jax.ops.segment_sum(
                jnp.ones(cap_r, dtype=jnp.int32), park,
                num_segments=n_codes + 1)[:n_codes]
            starts = jnp.cumsum(bc) - bc  # exclusive prefix in code order
            safe_l = jnp.clip(l_codes, 0, n_codes - 1)
            lo = starts[safe_l].astype(jnp.int32)
            counts = jnp.where(valid_l & (l_codes >= 0), bc[safe_l],
                               0).astype(jnp.int32)
            total = jnp.sum(counts.astype(jnp.int64))
            matched_l = counts > 0
            return (lo, counts, total, matched_l,
                    rs_perm, live_l, live_r)

        return probe

    # -- phase B: gather-map expansion --------------------------------------
    def expand(self, kind: str, out_cap: int, cap_l: int, cap_r: int, args):
        tkey = (kind, out_cap, cap_l, cap_r)
        fn = self._gather_traces.get(tkey)
        if fn is None:
            fn = tpu_jit(self._build_expand(kind, out_cap, cap_l))
            self._gather_traces[tkey] = fn
        return fn(*args)

    @staticmethod
    def _build_expand(kind: str, out_cap: int, cap_l: int):
        def expand_inner(lo, counts, rs_perm, live_l):
            """(li, ri, nout) for inner; counts pre-adjusted for left-outer.
            All i32: per-batch output capacities stay under 2^31 (bigger
            couldn't be materialized)."""
            csum = jnp.cumsum(counts)
            total = csum[-1] if counts.shape[0] else jnp.asarray(0, jnp.int32)
            off = csum - counts  # exclusive prefix
            j = jnp.arange(out_cap, dtype=jnp.int32)
            # source row per output slot: scatter each emitting row's index
            # at its start offset, then a running max fills the gaps — one
            # scan instead of a log(n)-gather searchsorted
            starts = jnp.where(counts > 0, off, out_cap)
            marks = jnp.zeros(out_cap, dtype=jnp.int32).at[starts].max(
                jnp.arange(counts.shape[0], dtype=jnp.int32), mode="drop")
            i = jax.lax.associative_scan(jnp.maximum, marks)
            i = jnp.clip(i, 0, cap_l - 1)
            delta = j - off[i]
            rpos = lo[i] + delta
            rpos = jnp.clip(rpos, 0, rs_perm.shape[0] - 1)
            ri = rs_perm[rpos].astype(jnp.int32)
            out_live = j < total
            li = jnp.where(out_live, i, 0)
            ri = jnp.where(out_live, ri, 0)
            return li, ri, total, out_live

        if kind == "inner":
            def f(lo, counts, rs_perm, live_l):
                li, ri, total, out_live = expand_inner(lo, counts, rs_perm, live_l)
                return li, ri, jnp.zeros(out_cap, jnp.bool_), jnp.zeros(out_cap, jnp.bool_), total
            return f

        if kind == "leftouter":
            def f(lo, counts, rs_perm, live_l):
                # unmatched live left rows emit exactly one null-right row
                counts2 = jnp.where(live_l & (counts == 0), 1, counts)
                li, ri, total, out_live = expand_inner(lo, counts2, rs_perm, live_l)
                null_r = (counts[li] == 0) & out_live
                ri = jnp.where(null_r, 0, ri)
                return li, ri, jnp.zeros(out_cap, jnp.bool_), null_r, total
            return f

        if kind == "fullouter":
            def f(lo, counts, rs_perm, live_l, r_unmatched):
                counts2 = jnp.where(live_l & (counts == 0), 1, counts)
                li, ri, total_l, out_live = expand_inner(lo, counts2, rs_perm, live_l)
                null_r = (counts[li] == 0) & out_live
                # append unmatched build rows with null left
                extra_pos = jnp.cumsum(r_unmatched.astype(jnp.int32)) - 1
                n_extra = jnp.sum(r_unmatched.astype(jnp.int32))
                tgt = jnp.where(r_unmatched, total_l + extra_pos, out_cap)
                ridx = jnp.arange(r_unmatched.shape[0], dtype=jnp.int32)
                ri = ri.at[tgt].set(ridx, mode="drop")
                li = li.at[tgt].set(0, mode="drop")
                null_l = jnp.zeros(out_cap, jnp.bool_).at[tgt].set(True, mode="drop")
                null_r = null_r & ~null_l
                total = total_l + n_extra
                return li, ri, null_l, null_r, total
            return f

        raise ColumnarProcessingError(f"expand kind {kind}")


class _DirectJoinKernel:
    """Dense-domain direct-address join — the TPU answer to the build-side
    hash table (reference: GpuHashJoin.scala builds a cuDF hash table and
    probes it). Pointer-chasing hash tables are VPU-hostile, but the common
    case — a fact table probing a dimension/key table whose integer keys
    occupy a bounded range (every foreign-key join) — needs no hash and no
    sort: scatter build row ids into a static-capacity table indexed by
    ``key - min(key)``, gather per probe key, done. One fused kernel does
    probe + gather + compaction with ZERO host syncs; two device flags
    (range fits, build keys unique) validate the speculation at collect
    time (runtime/speculation.py), falling back to the sort-based join via
    replay when the keys are too sparse or duplicated."""

    _traces = {}

    SUPPORTED = ("inner", "left", "leftouter", "leftsemi", "leftanti")

    @classmethod
    def run(cls, jt: str, lt: DeviceTable, rt: DeviceTable,
            lkey: DevVal, rkey: DevVal, H: int, masked_out: bool):
        """Returns ([(data, validity)...] for left cols [+ right cols],
        live_out_or_None, nout_dev, fail_dev). With ``masked_out`` the
        output stays IN PLACE (live rows marked by the returned mask — no
        compaction scatter at all, columnar/table.py DeviceTable.live);
        otherwise inner/semi/anti compact as before."""
        from spark_rapids_tpu import kernels
        key = (jt, H, lt.capacity, rt.capacity, masked_out,
               lt.live is not None, kernels.trace_token(),
               lt.schema_key()[0], rt.schema_key()[0],
               str(lkey[0].dtype), str(rkey[0].dtype))
        fn = cls._traces.get(key)
        if fn is None:
            fn = tpu_jit(cls._build(jt, H, lt.capacity, rt.capacity,
                                    masked_out))
            cls._traces[key] = fn
        l_cols = tuple((c.data, c.validity) for c in lt.columns)
        r_cols = tuple((c.data, c.validity) for c in rt.columns)
        return fn(l_cols, lkey, r_cols, rkey, lt.nrows_dev, rt.nrows_dev,
                  lt.live)

    @staticmethod
    def _build(jt: str, H: int, cap_l: int, cap_r: int, masked_out: bool):
        def kernel(l_cols, lk, r_cols, rk, nl, nr, live_l_mask):
            ld, lv = lk
            rd, rv = rk
            if live_l_mask is not None:
                live_l = live_l_mask
            else:
                live_l = jnp.arange(cap_l, dtype=jnp.int32) < nl
            live_r = jnp.arange(cap_r, dtype=jnp.int32) < nr
            vl = lv & live_l
            vr = rv & live_r

            rd64 = rd.astype(jnp.int64)
            ld64 = ld.astype(jnp.int64)
            I64MAX = jnp.asarray(np.iinfo(np.int64).max, jnp.int64)
            keymin = jnp.min(jnp.where(vr, rd64, I64MAX))
            any_r = jnp.any(vr)
            keymin = jnp.where(any_r, keymin, 0)
            pos = rd64 - keymin
            fits = (~any_r) | (jnp.max(jnp.where(vr, pos, 0)) < H)
            posc = jnp.clip(pos, 0, H - 1).astype(jnp.int32)
            tgt_r = jnp.where(vr, posc, H)
            cnt = jnp.zeros(H, jnp.int32).at[tgt_r].add(1, mode="drop")
            unique = jnp.max(cnt) <= 1
            rowid = jnp.full(H, -1, jnp.int32).at[tgt_r].max(
                jnp.arange(cap_r, dtype=jnp.int32), mode="drop")

            p = ld64 - keymin
            inb = (p >= 0) & (p < H) & vl
            ri = rowid[jnp.clip(p, 0, H - 1).astype(jnp.int32)]
            matched = inb & (ri >= 0)
            fail = ~(fits & unique)
            safe_ri = jnp.where(matched, ri, 0)

            if jt == "leftouter" or jt == "left":
                # every live probe row emits exactly one output row in place
                outs = list(l_cols)
                for d, v in r_cols:
                    outs.append((d[safe_ri], v[safe_ri] & matched))
                nl_out = (jnp.sum(live_l.astype(jnp.int32))
                          if live_l_mask is not None else nl)
                return tuple(outs), live_l_mask, nl_out, fail

            if jt in ("leftsemi", "leftanti"):
                keep = matched if jt == "leftsemi" else (live_l & ~matched)
            else:  # inner
                keep = matched
            nout = jnp.sum(keep.astype(jnp.int32))
            if masked_out:
                # deferred compaction: rows stay in place, keep is the mask
                outs = list(l_cols)
                if jt == "inner":
                    for d, v in r_cols:
                        outs.append((d[safe_ri], v[safe_ri] & matched))
                return tuple(outs), keep, nout, fail
            from spark_rapids_tpu.ops.scatter32 import compact_pairs
            pairs = list(l_cols)
            if jt == "inner":
                pairs += [(d[safe_ri], v[safe_ri] & matched)
                          for d, v in r_cols]
            outs, _ = compact_pairs([d for d, _ in pairs],
                                    [v for _, v in pairs], keep, cap_l)
            return tuple(outs), None, nout, fail

        return kernel


class _ColumnGather:
    """Jitted column gather per (out_cap, schema shapes)."""

    _traces = {}

    @classmethod
    def run(cls, table: DeviceTable, idx, null_mask, out_live, out_cap):
        key = (out_cap, table.capacity, table.schema_key()[0])
        fn = cls._traces.get(key)
        if fn is None:
            cap = table.capacity

            def gather(datas, valids, idx, null_mask, out_live):
                safe = jnp.clip(idx, 0, cap - 1)
                out = []
                for d, v in zip(datas, valids):
                    out.append((d[safe], v[safe] & ~null_mask & out_live))
                return out

            fn = tpu_jit(gather)
            cls._traces[key] = fn
        datas = tuple(c.data for c in table.columns)
        valids = tuple(c.validity for c in table.columns)
        outs = fn(datas, valids, idx, null_mask, out_live)
        return [DeviceColumn(c.dtype, d, v, dictionary=c.dictionary,
                             dict_sorted=c.dict_sorted, domain=c.domain)
                for c, (d, v) in zip(table.columns, outs)]


def _unify_string_keys(lcol: DeviceColumn, rcol: DeviceColumn):
    """Remap two dictionary-coded string columns into the union dictionary
    so codes compare across tables. Host work is O(dict size)."""
    ldict = lcol.dictionary if lcol.dictionary is not None else np.array([], dtype=object)
    rdict = rcol.dictionary if rcol.dictionary is not None else np.array([], dtype=object)
    union = np.unique(np.concatenate([ldict.astype(object), rdict.astype(object)]))
    lmap = np.searchsorted(union, ldict).astype(np.int32)
    rmap = np.searchsorted(union, rdict).astype(np.int32)
    from spark_rapids_tpu.dispatch import device_const
    lmap_d = device_const(lmap if len(lmap) else np.zeros(1, np.int32))
    rmap_d = device_const(rmap if len(rmap) else np.zeros(1, np.int32))
    lcodes = lmap_d[jnp.clip(lcol.data, 0, max(len(ldict) - 1, 0))]
    rcodes = rmap_d[jnp.clip(rcol.data, 0, max(len(rdict) - 1, 0))]
    return (lcodes, lcol.validity), (rcodes, rcol.validity)


class TpuJoinExec(TpuExec):
    def __init__(self, left: TpuExec, right: TpuExec, join_type: str,
                 left_keys: Sequence[Expression], right_keys: Sequence[Expression],
                 condition: Optional[Expression],
                 left_schema, right_schema,
                 subpartition_bytes: int = 1 << 30,
                 max_subpartitions: int = 64):
        super().__init__()
        self.children = (left, right)
        self.join_type = join_type.lower().replace("_", "")
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.condition = condition
        self.left_names = [n for n, _ in left_schema]
        self.right_names = [n for n, _ in right_schema]
        self._left_schema = left_schema
        self._right_schema = right_schema
        self.subpartition_bytes = subpartition_bytes
        self.max_subpartitions = max_subpartitions
        self._kernel = JoinKernel.get(len(self.left_keys))
        self._filter_kernel = None
        self._site_base = "join:{}:{}:{}:{}:{}".format(
            self.join_type,
            tuple(k.key() for k in self.left_keys),
            tuple(k.key() for k in self.right_keys),
            tuple(self.left_names), tuple(self.right_names))

    @property
    def _site_key(self) -> str:
        """Speculation site identity: join shape + PLAN POSITION (lore id,
        assigned deterministically per plan walk) so two same-shaped join
        operators — repeated subqueries, look-alike joins in unrelated
        queries — do not share one blocklist entry (ADVICE r3). A repeated
        identical query re-assigns the same lore id, so blocklisting still
        sticks across executions."""
        return f"{self._site_base}:op{getattr(self, '_lore_id', 0)}"

    def output_schema(self):
        jt = self.join_type
        ls = list(self._left_schema)
        rs = list(self._right_schema)
        if jt in ("leftsemi", "leftanti"):
            return ls
        # outer sides become nullable but DataType carries no nullability here
        return ls + rs

    def describe(self):
        return f"TpuJoin[{self.join_type}, keys={len(self.left_keys)}]"

    # -----------------------------------------------------------------------
    produces_masked = True

    def execute_masked(self):
        """Probe-side STREAMING execution: the build side is one coalesced
        (spillable-protected) table; probe batches stream through one at a
        time — the reference's join iterator shape (GpuShuffledHashJoinExec
        streams the streamed side against the built hash table). Full-outer
        joins accumulate a build-side match bitmap across probe batches and
        emit unmatched build rows as a final batch. Probe batches may be
        MASKED (filter output) and direct-join outputs stay masked —
        liveness rides a device mask instead of a compaction scatter."""
        from spark_rapids_tpu.runtime.retry import retry_block

        jt = self.join_type
        swapped = jt in ("right", "rightouter")
        build_child = self.children[0] if swapped else self.children[1]
        probe_child = self.children[1] if swapped else self.children[0]

        build = self._single(build_child)

        # spill-aware threshold: a build side past the device budget's
        # chunk share sub-partitions even when the conf threshold is
        # higher — each partition rides the spill tiers independently
        # instead of pinning one over-budget resident table
        from spark_rapids_tpu.runtime.memory import MEMORY
        sub_bytes = self.subpartition_bytes
        if sub_bytes > 0:
            sub_bytes = min(sub_bytes, MEMORY.scan_chunk_bytes())
        nparts = 1
        if (jt != "cross" and sub_bytes > 0
                and build.device_nbytes() > sub_bytes):
            nparts = min(
                -(-build.device_nbytes() // sub_bytes),
                self.max_subpartitions)
        if nparts > 1:
            yield from self._execute_subpartitioned(
                build, probe_child, swapped, int(nparts))
            return

        # the build side registers as a SpillableDeviceTable (ISSUE 15):
        # pinned only while one probe batch joins, so between batches —
        # while the probe child computes, possibly paying its own
        # memory pressure — the idle build table may ride the
        # device->host->disk tiers and re-land at its original
        # capacity for the next probe (traces and the full-outer match
        # bitmap key on that capacity staying put)
        from spark_rapids_tpu.runtime.spill import (
            BufferCatalog,
            PRIORITY_ACTIVE,
            SpillableDeviceTable,
        )
        build_sb = SpillableDeviceTable(build, BufferCatalog.get(),
                                        priority=PRIORITY_ACTIVE)
        build_cap = build.capacity
        del build
        full_outer = jt in ("full", "fullouter", "outer")
        r_matched_accum = None
        try:
            for pb in probe_child.execute_masked():
                with build_sb.pinned_batch() as bt:
                    out, r_matched = retry_block(
                        lambda b=pb, bb=bt: self._join_batch(
                            b, bb, swapped))
                if full_outer:
                    r_matched_accum = (
                        r_matched if r_matched_accum is None
                        else r_matched_accum | r_matched)
                if out is not None:
                    yield self._apply_condition(out)
                self.add_metric("probeBatches", 1)

            if full_outer:
                if r_matched_accum is None:
                    r_matched_accum = jnp.zeros(build_cap, jnp.bool_)
                with build_sb.pinned_batch() as bt:
                    yield self._unmatched_build_batch(
                        bt, r_matched_accum, swapped)
        finally:
            build_sb.release()

    def _execute_subpartitioned(self, build: DeviceTable, probe_child,
                                swapped: bool, nparts: int):
        """Sub-partitioned escalation (GpuSubPartitionHashJoin analog): the
        build table splits by Spark-exact key hash into ``nparts`` SPILLABLE
        partitions; each probe batch splits the same way, and bucket pairs
        join independently — peak HBM is one build partition + one probe
        sub-batch, not the whole build."""
        from spark_rapids_tpu.runtime.retry import retry_block
        from spark_rapids_tpu.runtime.spill import BufferCatalog, SpillableBatch
        from spark_rapids_tpu.shuffle.partitioning import HashPartitioner

        jt = self.join_type
        full_outer = jt in ("full", "fullouter", "outer")
        build_keys = self.left_keys if swapped else self.right_keys
        probe_keys = self.right_keys if swapped else self.left_keys
        bparter = HashPartitioner(build_keys, nparts)
        pparter = HashPartitioner(probe_keys, nparts)
        catalog = BufferCatalog.get()

        build_parts = [SpillableBatch(t, catalog)
                       for t in self._split(build, bparter)]
        del build
        self.add_metric("subPartitions", nparts)
        r_matched = [None] * nparts
        try:
            for pb in probe_child.execute_masked():
                for p, pp in enumerate(self._split(pb, pparter)):
                    with build_parts[p].pinned_batch() as bt:
                        out, rm = retry_block(
                            lambda a=pp, b=bt: self._join_batch(a, b, swapped))
                    if full_outer and rm is not None:
                        r_matched[p] = (rm if r_matched[p] is None
                                        else r_matched[p] | rm)
                    if out is not None:
                        yield self._apply_condition(out)
                self.add_metric("probeBatches", 1)

            if full_outer:
                for p in range(nparts):
                    with build_parts[p].pinned_batch() as bt:
                        rm = (r_matched[p] if r_matched[p] is not None
                              else jnp.zeros(bt.capacity, jnp.bool_))
                        yield self._unmatched_build_batch(bt, rm, swapped)
        finally:
            for sb in build_parts:
                sb.release()

    def _split(self, table: DeviceTable, parter) -> List[DeviceTable]:
        """Split a table into per-partition compacted tables, re-bucketed
        to their live size (one host sync for the count vector)."""
        pids = parter.partition_ids(table)
        live = table.row_mask()
        nparts = parter.num_partitions
        key = ("splitcnt", table.capacity, nparts)
        fn = self._kernel._aux_traces.get(key)
        if fn is None:
            def counts_fn(pids, live):
                return jax.ops.segment_sum(
                    live.astype(jnp.int32), jnp.clip(pids, 0, nparts - 1),
                    num_segments=nparts)
            fn = tpu_jit(counts_fn)
            self._kernel._aux_traces[key] = fn
        from spark_rapids_tpu.dispatch import host_fetch
        counts = np.asarray(host_fetch(fn(pids, live)))
        parts = []
        for p in range(nparts):
            compacted = self._compact(table, (pids == p) & live)
            k = bucket_for(max(int(counts[p]), 1))
            if k < compacted.capacity:
                cols = [c.with_arrays(c.data[:k], c.validity[:k])
                        for c in compacted.columns]
                compacted = DeviceTable(compacted.names, cols,
                                        int(counts[p]), k)
            parts.append(compacted)
        return parts


    def _apply_condition(self, out: DeviceTable) -> DeviceTable:
        if self.condition is not None and self.join_type in ("inner", "cross"):
            from spark_rapids_tpu.execs.base import MASKED_ENABLED
            from spark_rapids_tpu.execs.basic import _FilterKernel
            if self._filter_kernel is None:
                self._filter_kernel = _FilterKernel(self.condition)
            out = self._filter_kernel(out,
                                      emit_mask=MASKED_ENABLED.get())
        return out

    @staticmethod
    def _single(child: TpuExec) -> DeviceTable:
        batches = list(child.execute())
        if len(batches) != 1:
            raise ColumnarProcessingError("join requires a coalesced build side")
        return batches[0]

    def _join_batch(self, lt: DeviceTable, rt: DeviceTable, swapped: bool):
        """Join ONE probe batch (lt) against the build table (rt). Returns
        (output table or None, build-match bitmap or None)."""
        jt = self.join_type
        if jt == "cross":
            return self._cross(lt, rt, swapped), None

        if swapped:
            lkeys_e, rkeys_e = self.right_keys, self.left_keys
        else:
            lkeys_e, rkeys_e = self.left_keys, self.right_keys

        lkey_cols = compile_project(lkeys_e, lt)
        rkey_cols = compile_project(rkeys_e, rt)

        lkeys, rkeys = [], []
        for lc, rc in zip(lkey_cols, rkey_cols):
            if isinstance(lc.dtype, T.StringType):
                lk, rk = _unify_string_keys(lc, rc)
            else:
                lk, rk = (lc.data, lc.validity), (rc.data, rc.validity)
            lkeys.append(lk)
            rkeys.append(rk)

        full_outer = jt in ("full", "fullouter", "outer")

        direct = self._try_direct(jt, lt, rt, lkeys, rkeys, swapped,
                                  full_outer)
        if direct is not None:
            return direct, None

        probe_out = self._try_hashprobe(lt, rt, lkeys, rkeys)
        if probe_out is None:
            probe_out = self._kernel.probe(
                lkeys, rkeys, lt.nrows_dev, rt.nrows_dev,
                lt.capacity, rt.capacity, lt.live)
        (lo, counts, total_d, matched_l, rs_perm, live_l, live_r) = probe_out

        r_matched = None
        if full_outer:
            r_matched = self._right_matched(lo, counts, rs_perm, rt.capacity,
                                            lt.capacity)

        if jt in ("leftsemi", "leftanti"):
            from spark_rapids_tpu.execs.base import MASKED_ENABLED
            keep = matched_l if jt == "leftsemi" else ~matched_l
            keep = keep & live_l
            if MASKED_ENABLED.get():
                nkeep = self._mask_count(keep)
                return DeviceTable(lt.names, lt.columns, nkeep,
                                   lt.capacity, live=keep), None
            return self._compact(lt, keep), None

        from spark_rapids_tpu.runtime import speculation as spec
        size_site = self._site_key + ":size"
        ctx = None if full_outer else spec.allowed(size_site)
        if ctx is not None:
            # speculative static bound: FK-join shape — output rows fit the
            # probe side's bucket. The exact i64 total stays on device; the
            # flag is validated by the collect's packed fetch and a miss
            # replays this site on the exact path below.
            out_cap = bucket_for(max(lt.capacity, 1))
            ctx.add_flag(size_site, self._size_flag(
                jt, total_d, counts, live_l, out_cap, lt.capacity))
        else:
            from spark_rapids_tpu.dispatch import host_fetch
            total = int(host_fetch(total_d))  # one host sync per batch
            if jt in ("left", "leftouter", "right", "rightouter") or full_outer:
                # each unmatched probe row adds at most one output row; use
                # the probe CAPACITY as the static bound rather than paying a
                # second tunnel round trip for the exact count (<=2x bucket)
                upper = total + lt.capacity
            else:
                upper = total
            out_cap = bucket_for(max(upper, 1))

        if jt == "inner":
            li, ri, null_l, null_r, nout = self._kernel.expand(
                "inner", out_cap, lt.capacity, rt.capacity,
                (lo, counts, rs_perm, live_l))
        else:  # left/right outer per batch; full outer = left outer per
            # batch + deferred unmatched-build batch
            li, ri, null_l, null_r, nout = self._kernel.expand(
                "leftouter", out_cap, lt.capacity, rt.capacity,
                (lo, counts, rs_perm, live_l))

        out_live = jnp.arange(out_cap, dtype=jnp.int32) < nout
        lcols = _ColumnGather.run(lt, li, null_l, out_live, out_cap)
        rcols = _ColumnGather.run(rt, ri, null_r, out_live, out_cap)

        names = self.left_names + self.right_names
        cols = rcols + lcols if swapped else lcols + rcols
        return DeviceTable(names, cols, nout, out_cap), r_matched

    def _size_flag(self, jt, total_d, counts, live_l, out_cap, cap_l):
        """Device bool: True iff the speculative out_cap was too small.
        i64 throughout so a pathological many-to-many total can't wrap."""
        key = ("sizeflag", jt, out_cap, cap_l, counts.shape[0])
        fn = self._kernel._aux_traces.get(key)
        if fn is None:
            outer = jt in ("left", "leftouter", "right", "rightouter")

            def flag(total_d, counts, live_l):
                tot = total_d.astype(jnp.int64)
                if outer:
                    tot = tot + jnp.sum(
                        (live_l & (counts == 0)).astype(jnp.int64))
                return tot > out_cap

            fn = tpu_jit(flag)
            self._kernel._aux_traces[key] = fn
        return fn(total_d, counts, live_l)

    def _try_hashprobe(self, lt, rt, lkeys, rkeys):
        """Pallas hash-probe (kernels/hashprobe.py): for single
        integer-key joins, one bounded-attempt hash table replaces the
        dense-rank sort chain. Outputs are probe()-compatible ranges
        (counts in {0,1}, identity perm) so every downstream consumer —
        expand, outer nulls, the full-outer match bitmap — runs
        unchanged. Unique-build-key speculation: the device ``fail``
        flag (duplicate keys or table overflow) rides the collect's
        packed fetch; a miss blocklists this site and replays on the
        sort-based probe — the _DirectJoinKernel protocol. Returns None
        when the shape doesn't qualify."""
        from spark_rapids_tpu import kernels
        if len(lkeys) != 1:
            return None
        if not (getattr(lkeys[0][0], "ndim", 1) == 1
                and getattr(rkeys[0][0], "ndim", 1) == 1):
            # decimal128 keys are (rows, 2) limb MATRICES — the scalar
            # two-limb split does not apply; sorted probe handles them
            return None
        if not (jnp.issubdtype(lkeys[0][0].dtype, jnp.integer)
                and jnp.issubdtype(rkeys[0][0].dtype, jnp.integer)):
            return None
        if not kernels.enabled("hashprobe"):
            # qualifying shape, primitive disabled/demoted: counted
            # ONCE per exec per query (this runs per probe BATCH; the
            # other routers count once per trace — a per-batch count
            # would swamp the fallback ratio)
            if not getattr(self, "_hashprobe_off_counted", False):
                self._hashprobe_off_counted = True
                return kernels.count_fallback("hashprobe", lambda: None)
            return None
        from spark_rapids_tpu.runtime import speculation as spec
        site = self._site_key + ":hashprobe"
        ctx = spec.allowed(site)
        if ctx is None:
            return None
        H = 1 << max(2 * rt.capacity - 1, 1).bit_length()
        attempts = kernels.config().attempts
        tkey = ("hashprobe", H, lt.capacity, rt.capacity,
                lt.live is not None, attempts, kernels.trace_token(),
                str(lkeys[0][0].dtype), str(rkeys[0][0].dtype))
        fn = self._kernel._probe_traces.get(tkey, "absent")
        if fn is None:
            return None  # memoized ineligible shape: sorted path
        if fn == "absent":
            cap_l, cap_r = lt.capacity, rt.capacity

            def hashprobe(lk, rk, nl, nr, live_l_mask):
                from spark_rapids_tpu.kernels import hashprobe as khash
                if live_l_mask is not None:
                    live_l = live_l_mask
                else:
                    live_l = jnp.arange(cap_l, dtype=jnp.int32) < nl
                live_r = jnp.arange(cap_r, dtype=jnp.int32) < nr
                lo, counts, total, matched, rs_perm, fail = \
                    khash.probe_ranges(lk, rk, live_l, live_r, H,
                                       attempts)
                return (lo, counts, total, matched, rs_perm,
                        live_l, live_r, fail)

            # resolution is counted ONCE per trace key (trace-time
            # semantics, like the other primitives' routers) and an
            # ineligible shape is MEMOIZED — without the sentinel every
            # probe batch would re-trace probe_ranges just to raise and
            # fall back again
            from spark_rapids_tpu.dispatch import COMPILE_SCOPE
            from spark_rapids_tpu.kernels import KernelIneligible
            fn = tpu_jit(hashprobe)
            try:
                out = fn(lkeys[0], rkeys[0], lt.nrows_dev, rt.nrows_dev,
                         lt.live)
            except KernelIneligible:
                COMPILE_SCOPE.add("hloFallbacks", 1)
                self._kernel._probe_traces[tkey] = None
                return None
            except Exception as exc:
                from spark_rapids_tpu.runtime.crash_handler import (
                    is_fatal_device_error,
                )
                from spark_rapids_tpu.runtime.retry import is_device_oom
                if is_device_oom(exc) or is_fatal_device_error(exc):
                    # OOMs belong to the retry framework; a dead
                    # device/tunnel is the health monitor's to recover
                    # — neither is the kernel's fault (the tpu_jit
                    # capture handler makes the same exemptions)
                    raise
                # idempotent when tpu_jit's capture frame already did it
                kernels.demote("hashprobe", exc)
                COMPILE_SCOPE.add("hloFallbacks", 1)
                return None
            COMPILE_SCOPE.add("pallasKernels", 1)
            self._kernel._probe_traces[tkey] = fn
        else:
            out = fn(lkeys[0], rkeys[0], lt.nrows_dev, rt.nrows_dev,
                     lt.live)
        ctx.add_flag(site, out[-1])
        self.add_metric("hashProbeBatches", 1)
        return out[:-1]

    def _try_direct(self, jt, lt, rt, lkeys, rkeys, swapped, full_outer):
        """Dense-domain direct-address fast path (see _DirectJoinKernel).
        Returns the output table, or None when the shape doesn't qualify
        (multi-key, non-integer key, full outer, residual condition on a
        non-inner join, or a prior failure blocklisted the site)."""
        if (len(lkeys) != 1 or full_outer
                or jt not in _DirectJoinKernel.SUPPORTED):
            return None
        if not (jnp.issubdtype(lkeys[0][0].dtype, jnp.integer)
                and jnp.issubdtype(rkeys[0][0].dtype, jnp.integer)):
            return None
        from spark_rapids_tpu.runtime import speculation as spec
        site = self._site_key + ":direct"
        ctx = spec.allowed(site)
        if ctx is None:
            return None
        from spark_rapids_tpu.execs.base import MASKED_ENABLED
        masked_out = MASKED_ENABLED.get()
        H = bucket_for(max(DIRECT_TABLE_MULT.get() * rt.capacity, 1))
        outs, live_out, nout, fail = _DirectJoinKernel.run(
            jt, lt, rt, lkeys[0], rkeys[0], H, masked_out)
        ctx.add_flag(site, fail)
        self.add_metric("directJoinBatches", 1)
        if jt in ("leftsemi", "leftanti"):
            cols = [c.with_arrays(d, v)
                    for c, (d, v) in zip(lt.columns, outs)]
            return DeviceTable(lt.names, cols, nout, lt.capacity,
                               live=live_out)
        lcols = [c.with_arrays(d, v)
                 for c, (d, v) in zip(lt.columns, outs[:len(lt.columns)])]
        rcols = []
        for c, (d, v) in zip(rt.columns, outs[len(lt.columns):]):
            rcols.append(DeviceColumn(c.dtype, d, v, dictionary=c.dictionary,
                                      dict_sorted=c.dict_sorted,
                                      domain=c.domain))
        names = self.left_names + self.right_names
        cols = rcols + lcols if swapped else lcols + rcols
        return DeviceTable(names, cols, nout, lt.capacity, live=live_out)

    def _unmatched_build_batch(self, rt: DeviceTable, r_matched,
                               swapped: bool) -> DeviceTable:
        """Full outer tail: build rows no probe batch matched, with an
        all-null probe side."""
        live_r = rt.row_mask()
        compacted = self._compact(rt, live_r & ~r_matched)
        probe_schema = self._right_schema if swapped else self._left_schema
        null_cols = []
        for _, dt in probe_schema:
            if isinstance(dt, T.StringType):
                data = jnp.zeros(compacted.capacity, dtype=jnp.int32)
                null_cols.append(DeviceColumn(
                    dt, data, jnp.zeros(compacted.capacity, jnp.bool_),
                    dictionary=np.array([], dtype=object)))
            else:
                from spark_rapids_tpu.columnar.column import null_data_array
                null_cols.append(DeviceColumn(
                    dt, null_data_array(dt, compacted.capacity),
                    jnp.zeros(compacted.capacity, jnp.bool_)))
        names = self.left_names + self.right_names
        cols = (list(compacted.columns) + null_cols if swapped
                else null_cols + list(compacted.columns))
        return DeviceTable(names, cols, compacted.nrows_dev,
                           compacted.capacity)

    def _right_matched(self, lo, counts, rs_perm, cap_r: int, cap_l: int):
        """Which build rows matched at least one probe row: mark sorted
        positions [lo_i, lo_i+count_i) then scatter through rs_perm."""
        key = ("rmatch", cap_l, cap_r)
        fn = self._kernel._aux_traces.get(key)
        if fn is None:
            def rmatch(lo, counts, rs_perm):
                # diff trick: +1 at lo, -1 at lo+count, prefix-sum > 0
                marks = jnp.zeros(cap_r + 1, dtype=jnp.int32)
                marks = marks.at[jnp.clip(lo, 0, cap_r)].add(
                    jnp.where(counts > 0, 1, 0), mode="drop")
                ends = jnp.clip(lo + counts, 0, cap_r)
                marks = marks.at[ends].add(jnp.where(counts > 0, -1, 0), mode="drop")
                covered_sorted = jnp.cumsum(marks[:-1]) > 0
                return jnp.zeros(cap_r, jnp.bool_).at[rs_perm].set(covered_sorted)
            fn = tpu_jit(rmatch)
            self._kernel._aux_traces[key] = fn
        return fn(lo, counts, rs_perm)

    def _mask_count(self, keep):
        key = ("maskcount", keep.shape[0])
        fn = self._kernel._aux_traces.get(key)
        if fn is None:
            fn = tpu_jit(lambda k: jnp.sum(k.astype(jnp.int32)))
            self._kernel._aux_traces[key] = fn
        return fn(keep)

    def _compact(self, table: DeviceTable, keep) -> DeviceTable:
        """Semi/anti: compact kept rows (static capacity, like the filter
        kernel's scatter-to-cumsum compaction)."""
        from spark_rapids_tpu import kernels
        key = ("compact", table.capacity, table.schema_key()[0],
               kernels.trace_token())
        fn = self._kernel._aux_traces.get(key)
        if fn is None:
            cap = table.capacity

            def compact(datas, valids, keep):
                from spark_rapids_tpu.ops.scatter32 import compact_pairs
                return compact_pairs(datas, valids, keep, cap)

            fn = tpu_jit(compact)
            self._kernel._aux_traces[key] = fn
        datas = tuple(c.data for c in table.columns)
        valids = tuple(c.validity for c in table.columns)
        outs, new_n = fn(datas, valids, keep)
        cols = [c.with_arrays(d, v) for c, (d, v) in zip(table.columns, outs)]
        return DeviceTable(table.names, cols, new_n, table.capacity)

    def _cross(self, lt: DeviceTable, rt: DeviceTable,
               swapped: bool = False) -> DeviceTable:
        lt = lt.compacted()  # tiling needs the prefix invariant
        nl, nr = lt.num_rows, rt.num_rows
        out_cap = bucket_for(max(nl * nr, 1))
        key = ("cross", out_cap, lt.capacity, rt.capacity)
        fn = self._kernel._aux_traces.get(key)
        if fn is None:
            def cross_maps(nl_d, nr_d):
                j = jnp.arange(out_cap, dtype=jnp.int64)
                nr64 = jnp.maximum(nr_d.astype(jnp.int64), 1)
                li = j // nr64
                ri = j % nr64
                out_live = j < nl_d.astype(jnp.int64) * nr_d.astype(jnp.int64)
                return li, ri, out_live
            fn = tpu_jit(cross_maps)
            self._kernel._aux_traces[key] = fn
        li, ri, out_live = fn(lt.nrows_dev, rt.nrows_dev)
        zero = jnp.zeros(out_cap, jnp.bool_)
        lcols = _ColumnGather.run(lt, li, zero, out_live, out_cap)
        rcols = _ColumnGather.run(rt, ri, zero, out_live, out_cap)
        cols = rcols + lcols if swapped else lcols + rcols
        return DeviceTable(self.left_names + self.right_names, cols,
                           nl * nr, out_cap)
