"""Device-residency rules: the TPU-first contract (dispatch.py header)
that NOTHING transfers host<->device on a warm query outside the
sanctioned sites.

* RL-HOST-SYNC — no host synchronization (``jax.device_get``,
  ``.block_until_ready()``) inside execs/ or ops/ hot paths except via
  the sanctioned ``dispatch.host_fetch`` helper.
* RL-JNP-SCOPE — ``jax.numpy`` imports only in the device layers.
* RL-MESH-HOST — mesh-native execution keeps shards device-resident
  BETWEEN exchanges: inside ``parallel/`` and the shard-dispatch
  placement layer, host materialization may appear only at sanctioned
  gather points (``_MESH_HOST_ALLOWLIST``, each entry justified).
* RL-KERNEL-HOST — the Pallas kernel layer (``kernels/``) is pure
  device code that executes INSIDE other traces: any numpy
  materialization or host synchronization there would stall the trace
  or smuggle device data to the host mid-kernel.
* RL-MEM-ACCOUNT — device landings in execs//ops/ must route through
  arbiter-accounted paths (``DeviceTable.from_host``); a raw
  ``jax.device_put`` lands bytes the MemoryArbiter never sees.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make
from spark_rapids_tpu.lint.rules.common import (_attr_chain,
                                                _host_sync_call,
                                                _is_device_expr)

#: directories (under spark_rapids_tpu/) whose modules are device layers
#: and may import jax.numpy
_DEVICE_DIRS = ("execs", "ops", "columnar", "parallel", "runtime",
                "shuffle", "shims", "models", "kernels")
#: top-level device-layer files
_DEVICE_FILES = ("dispatch.py", "udf.py")


def _check_host_sync(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    in_hot_path = rel.startswith(("spark_rapids_tpu/execs/",
                                  "spark_rapids_tpu/ops/"))
    if not in_hot_path:
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            # `from jax import device_get` would make the call below
            # invisible to the chain matcher — ban the import form too
            for a in node.names:
                if a.name in ("device_get", "block_until_ready"):
                    diags.append(make(
                        "RL-HOST-SYNC", f"{rel}:{node.lineno}",
                        f"importing jax.{a.name} into a hot path; route "
                        "through dispatch.host_fetch so syncs are "
                        "counted and reviewable"))
            continue
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if chain.endswith(".block_until_ready"):
            diags.append(make(
                "RL-HOST-SYNC", f"{rel}:{node.lineno}",
                "block_until_ready() stalls the dispatch pipeline; use "
                "dispatch.host_fetch at a sanctioned sync point"))
        elif chain == "jax.device_get" or chain.endswith(".device_get") \
                or chain == "device_get":
            diags.append(make(
                "RL-HOST-SYNC", f"{rel}:{node.lineno}",
                "raw jax.device_get in a hot path (~0.1s tunnel stall "
                "each); route through dispatch.host_fetch so syncs are "
                "counted and reviewable"))
        elif chain in ("np.asarray", "numpy.asarray", "float", "int") \
                and node.args and _is_device_expr(node.args[0]):
            # the statically-decidable slice of "np.asarray/float/int on
            # device values": the argument is itself a jnp./jax. call,
            # so the conversion provably forces a device sync (general
            # deviceness needs dataflow a lint can't do)
            diags.append(make(
                "RL-HOST-SYNC", f"{rel}:{node.lineno}",
                f"{chain}() over a jax expression synchronizes the "
                "device; route through dispatch.host_fetch"))


def _check_jnp_scope(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    parts = rel.split("/")
    allowed = False
    if parts[0] != "spark_rapids_tpu":
        allowed = False  # bench.py / scale_test.py are host drivers
    elif len(parts) == 2:
        allowed = parts[1] in _DEVICE_FILES
    else:
        allowed = parts[1] in _DEVICE_DIRS
    if allowed:
        return
    for node in ast.walk(tree):
        hit = None
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy":
                    hit = f"{a.name} imported"
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax.numpy" or (
                    node.module == "jax"
                    and any(a.name == "numpy" for a in node.names)):
                hit = "jax.numpy imported"
        elif isinstance(node, ast.Attribute):
            # `import jax; jax.numpy.foo(...)` bypasses the import
            # check — catch the attribute access form too (exact match:
            # the inner `jax.numpy` node; avoids double-reporting the
            # enclosing `jax.numpy.foo` chain)
            if _attr_chain(node) == "jax.numpy":
                hit = "jax.numpy used"
        if hit:
            diags.append(make(
                "RL-JNP-SCOPE", f"{rel}:{node.lineno}",
                f"{hit} outside the device layers "
                f"({', '.join(_DEVICE_DIRS)}); host-side layers must "
                "stay device-agnostic"))


#: sanctioned mesh->host materialization points: "<rel>:<function>" ->
#: justification. The hook for new gather points — add an entry HERE
#: with a reason, never a bare suppression.
_MESH_HOST_ALLOWLIST = {
    "spark_rapids_tpu/parallel/mesh.py:mesh_gather":
        "THE sanctioned mesh->host gather point (routes through "
        "dispatch.host_fetch and counts meshGatherRows; the ICI "
        "exchange's per-shard live-count fetch comes through here)",
    "spark_rapids_tpu/parallel/mesh.py:MeshRuntime.configure":
        "np.array over a list of jax DEVICE HANDLES (building the Mesh "
        "topology array) — no device data is materialized",
    "spark_rapids_tpu/parallel/mesh.py:MeshRuntime.exchange_mesh":
        "np.array over jax device handles (submesh construction) — no "
        "device data is materialized",
}


def _check_mesh_host(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    """RL-MESH-HOST: inside parallel/ and the shard-dispatch placement
    layer, host materialization of device data (np.asarray on arrays,
    jax.device_get, dispatch.host_fetch, .block_until_ready(),
    .addressable_shards reads) is forbidden outside the sanctioned
    gather points — the static guard for 'zero host round-trips
    between exchanges': shards land once at the scan and stay
    device-resident until a sanctioned gather."""
    if not (rel.startswith("spark_rapids_tpu/parallel/")
            or rel == "spark_rapids_tpu/runtime/placement.py"):
        return

    def flag(node, what: str, func: Optional[str]):
        if f"{rel}:{func}" in _MESH_HOST_ALLOWLIST:
            return
        diags.append(make(
            "RL-MESH-HOST", f"{rel}:{node.lineno}",
            f"{what} in mesh/shard-dispatch code"
            + (f" (function {func!r})" if func else " (module level)")
            + " — device shards must stay resident between exchanges; "
            "gather through parallel.mesh.mesh_gather or allowlist the "
            "function in _MESH_HOST_ALLOWLIST with a justification"))

    def walk(node, func: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # QUALIFIED name (Class.method / outer.inner): a bare-name
            # key would exempt EVERY function sharing the allowlisted
            # name anywhere in the file
            func = f"{func}.{node.name}" if func else node.name
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain in ("np.asarray", "numpy.asarray", "asarray",
                         "np.array", "numpy.array"):
                # bare 'asarray' covers `from numpy import asarray`;
                # np.array() forces the same device->host copy
                flag(node, f"{chain}()", func)
            elif _host_sync_call(chain):
                flag(node, f"{chain}()", func)
        elif isinstance(node, ast.Attribute) \
                and node.attr == "addressable_shards":
            flag(node, ".addressable_shards read", func)
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, None)


#: sanctioned host-side operations inside kernels/:
#: "<rel>:<qualified function>" -> justification. The hook for new
#: exceptions — add an entry HERE with a reason, never a bare
#: suppression.
_KERNEL_HOST_ALLOWLIST = {}


def _check_kernel_host(rel: str, tree: ast.AST, diags: List[Diagnostic]):
    """RL-KERNEL-HOST: kernels/ modules run inside other traces — no
    numpy at all (materialization happens the moment an np.* call sees
    a device array) and no host syncs. The static guard for 'a Pallas
    primitive never stalls the program that embeds it'."""
    if not rel.startswith("spark_rapids_tpu/kernels/"):
        return

    def flag(node, what: str, func: Optional[str]):
        if f"{rel}:{func}" in _KERNEL_HOST_ALLOWLIST:
            return
        diags.append(make(
            "RL-KERNEL-HOST", f"{rel}:{node.lineno}",
            f"{what} in the Pallas kernel layer"
            + (f" (function {func!r})" if func else " (module level)")
            + " — kernels/ is pure device code traced into other "
            "programs; keep host work at the dispatch sites or "
            "allowlist the function in _KERNEL_HOST_ALLOWLIST with a "
            "justification"))

    def walk(node, func: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            func = f"{func}.{node.name}" if func else node.name
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = getattr(node, "module", None)
            names = [a.name for a in node.names]
            if mod == "numpy" or "numpy" in names \
                    or any(n.startswith("numpy.") for n in names) \
                    or (mod or "").startswith("numpy."):
                flag(node, "numpy import", func)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain.startswith(("np.", "numpy.")):
                flag(node, f"{chain}()", func)
            elif _host_sync_call(chain):
                flag(node, f"{chain}()", func)
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, None)


#: sanctioned raw device_put sites inside execs//ops/:
#: "<rel>:<qualified function>" -> justification. The hook for new
#: exceptions — add an entry HERE with a reason, never a bare
#: suppression. Table-sized landings are NEVER eligible: they belong
#: on the arbiter-accounted DeviceTable.from_host path.
_MEM_ACCOUNT_ALLOWLIST = {
    "spark_rapids_tpu/execs/mesh.py:TpuMeshRelandExec._reland":
        "re-lands a 4-element uint32 DIGEST scalar (gather-integrity "
        "checksum, ~16 bytes) onto device 0 — validation overhead, "
        "not a table landing; budget accounting at this size would be "
        "pure ledger noise",
}


def _check_mem_account(rel: str, tree: ast.AST,
                       diags: List[Diagnostic]):
    """RL-MEM-ACCOUNT: device landings in execs//ops/ must route
    through arbiter-accounted paths — a raw jax.device_put there lands
    bytes the MemoryArbiter never sees, and the hard budget contract
    (zero violations under scale_test --device-budget) silently
    breaks."""
    if not rel.startswith(("spark_rapids_tpu/execs/",
                           "spark_rapids_tpu/ops/")):
        return

    def flag(node, what: str, func):
        if f"{rel}:{func}" in _MEM_ACCOUNT_ALLOWLIST:
            return
        diags.append(make(
            "RL-MEM-ACCOUNT", f"{rel}:{node.lineno}",
            f"{what} in a device-landing layer"
            + (f" (function {func!r})" if func else " (module level)")
            + " — land through DeviceTable.from_host so the memory "
            "arbiter accounts the bytes, or allowlist the function in "
            "_MEM_ACCOUNT_ALLOWLIST with a justification"))

    def walk(node, func):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            func = f"{func}.{node.name}" if func else node.name
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            # `from jax import device_put` would make the call below
            # invisible to the chain matcher — ban the import form too
            for a in node.names:
                if a.name == "device_put":
                    flag(node, "importing jax.device_put", func)
        elif isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain == "jax.device_put" \
                    or chain.endswith(".device_put") \
                    or chain == "device_put":
                flag(node, f"{chain}()", func)
        for child in ast.iter_child_nodes(node):
            walk(child, func)

    walk(tree, None)
