"""Offline profiling / qualification tools over query event logs.

The spark-rapids-tools analog: ``python -m spark_rapids_tpu.tools
profile <eventlog>`` turns the JSONL event logs the engine writes
(``spark.rapids.sql.eventLog.enabled`` — obs/events.py) into a
machine-readable profiling report (top operators by self time, compute
vs transfer vs shuffle breakdown, per-exchange skew, spill/retry
summary, fallback inventory, span attribution), and ``... compare A B``
diffs two runs per-query/per-operator — the tool perf PRs cite instead
of hand-timing.

Operates purely on the JSON records — no session/runtime machinery is
touched, so the CLI runs anywhere the logs land (it shares only the
event-schema constant with obs/events.py).
"""

from spark_rapids_tpu.tools.report import (  # noqa: F401
    build_profile,
    load_events,
    render_profile,
)
from spark_rapids_tpu.tools.compare import (  # noqa: F401
    build_compare,
    render_compare,
)
