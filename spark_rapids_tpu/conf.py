"""Typed configuration registry (reference: RapidsConf.scala, 3,270 LoC,
236 spark.rapids.* keys -- SURVEY.md §2.10/§5).

Same design: a global registry of typed ConfEntry objects with defaults and
doc strings, a RapidsConf view over a plain dict, per-operator kill switches
registered dynamically by the rules layer, and markdown doc generation.
Keys keep the spark.rapids.* prefix so reference users can carry configs over.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

_REGISTRY: Dict[str, "ConfEntry"] = {}


@dataclass(frozen=True)
class ConfEntry:
    key: str
    default: Any
    doc: str
    conv: Callable[[str], Any]
    startup_only: bool = False
    commonly_used: bool = False
    internal: bool = False

    def get(self, conf: "RapidsConf") -> Any:
        return conf.get(self.key)


def _to_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("true", "1", "yes", "on")


def _conf(key, default, doc, conv, **kw) -> ConfEntry:
    e = ConfEntry(key=key, default=default, doc=doc, conv=conv, **kw)
    if key in _REGISTRY:
        raise ValueError(f"duplicate conf key {key}")
    _REGISTRY[key] = e
    return e


def bool_conf(key, default, doc, **kw):
    return _conf(key, default, doc, _to_bool, **kw)


def int_conf(key, default, doc, **kw):
    return _conf(key, default, doc, int, **kw)


def float_conf(key, default, doc, **kw):
    return _conf(key, default, doc, float, **kw)


def str_conf(key, default, doc, **kw):
    return _conf(key, default, doc, str, **kw)


def register_op_kill_switch(kind: str, name: str, default_enabled: bool, doc: str) -> ConfEntry:
    """Per-operator kill switch, auto-generated from rule registration like
    the reference's spark.rapids.sql.expression.* / sql.exec.* keys."""
    key = f"spark.rapids.sql.{kind}.{name}"
    if key in _REGISTRY:
        return _REGISTRY[key]
    return bool_conf(key, default_enabled, doc)


# ---------------------------------------------------------------------------
# Core entries (the ~30-key starter set from SURVEY.md §7 phase 2, growing
# toward the reference's full 236).
# ---------------------------------------------------------------------------

SQL_ENABLED = bool_conf(
    "spark.rapids.sql.enabled", True,
    "Master enable for plan rewriting onto the TPU.", commonly_used=True)

SQL_MODE = str_conf(
    "spark.rapids.sql.mode", "executeongpu",
    "executeongpu: rewrite and run on TPU; explainonly: tag the plan and "
    "report what would run on TPU without converting.")

EXPLAIN = str_conf(
    "spark.rapids.sql.explain", "NONE",
    "NONE, NOT_ON_GPU (log reasons for fallbacks) or ALL.", commonly_used=True)

BATCH_SIZE_BYTES = int_conf(
    "spark.rapids.sql.batchSizeBytes", 1 << 30,
    "Target device batch size in bytes for coalescing.", commonly_used=True)

MAX_READER_BATCH_SIZE_ROWS = int_conf(
    "spark.rapids.sql.reader.batchSizeRows", 1 << 20,
    "Soft cap on rows per batch produced by scans.")

CONCURRENT_TPU_TASKS = int_conf(
    "spark.rapids.sql.concurrentGpuTasks", 2,
    "Number of tasks that may hold the device semaphore concurrently "
    "(reference: GpuSemaphore).", commonly_used=True)

HBM_POOL_FRACTION = float_conf(
    "spark.rapids.memory.gpu.allocFraction", 0.9,
    "Fraction of visible HBM the engine may use.", startup_only=True)

HBM_RESERVE_BYTES = int_conf(
    "spark.rapids.memory.gpu.reserve", 640 << 20,
    "HBM held back from the pool for XLA scratch/fragmentation.",
    startup_only=True)

HOST_SPILL_STORAGE_SIZE = int_conf(
    "spark.rapids.memory.host.spillStorageSize", 1 << 31,
    "Bytes of host memory used for spilled device buffers before disk.")

PINNED_POOL_SIZE = int_conf(
    "spark.rapids.memory.pinnedPool.size", 0,
    "Host staging pool for H2D/D2H transfers (0 = unpooled).",
    startup_only=True)

HOST_MEMORY_LIMIT = int_conf(
    "spark.rapids.memory.host.limit", 4 << 30,
    "Host-memory arbiter budget for engine host buffers (shuffle "
    "serialization, cached blocks). Exhaustion spills the host tier to "
    "disk, then blocks, then raises CpuRetryOOM (HostAlloc analog).",
    startup_only=True)

RETRY_OOM_MAX_RETRIES = int_conf(
    "spark.rapids.memory.gpu.oomMaxRetries", 2,
    "Synchronous-spill retries before escalating to split-and-retry.")

SPECULATIVE_SIZING = bool_conf(
    "spark.rapids.tpu.speculativeSizing.enabled", True,
    "Size data-dependent outputs (join gather maps, direct-address join "
    "tables) speculatively with device-resident validation flags instead "
    "of a ~0.1s host sync per operator; a failed speculation replays the "
    "query on the exact path (runtime/speculation.py).", commonly_used=True)

COLUMN_PRUNING = bool_conf(
    "spark.rapids.tpu.sql.columnPruning.enabled", True,
    "Prune unreferenced columns below joins/aggregates (Spark's "
    "ColumnPruning logical rule, which the reference inherits from Spark; "
    "this engine owns its logical plans so it applies the rule itself — "
    "overrides/pruning.py). Every pruned column avoids per-operator "
    "gathers/scatters of emulated 64-bit halves on TPU.")

MASKED_BATCHES = bool_conf(
    "spark.rapids.tpu.maskedBatches.enabled", True,
    "Defer row compaction: filters and dense-key joins emit batches whose "
    "liveness is a device mask instead of scatter-compacting every column "
    "(the most expensive per-row op on TPU); mask-aware downstream execs "
    "consume the mask and the scatter is paid only at collect/spill/"
    "split boundaries (columnar/table.py DeviceTable.live).",
    commonly_used=True)

SEQUENCE_ELEMENT_MULT = int_conf(
    "spark.rapids.tpu.sequence.elementMultiplier", 4,
    "sequence() element buffer capacity as a multiple of the row "
    "capacity; outputs beyond it raise with this knob's name "
    "(static-shape sizing, ops/collections.Sequence).")

COLLECT_EMBED_ROWS_CAP = int_conf(
    "spark.rapids.tpu.collect.embedRowsCap", 1 << 16,
    "Collects of tables up to this capacity fetch the padded bucket with "
    "the row count embedded in the packed buffer instead of paying a "
    "separate ~0.1s row-count sync (columnar/table.py to_host).")

COLLECT_EMBED_MAX_BYTES = int_conf(
    "spark.rapids.tpu.collect.embedMaxBytes", 4 << 20,
    "...but only while the padded transfer stays under this many bytes "
    "(wide schemas fall back to the row-count sync).")

WINDOW_ROWS_FRAME_MAX_BOUND = int_conf(
    "spark.rapids.sql.window.rowsFrameMaxBound", 1 << 16,
    "Rows-frame window bounds beyond this magnitude tag CPU fallback "
    "(sparse-table/unroll widths are bounded by the frame's endpoints).")

NLJ_PAIR_BUDGET = int_conf(
    "spark.rapids.sql.nestedLoopJoin.pairBudget", 1 << 20,
    "Max probe-tile x build-row pairs materialized per nested-loop join "
    "tile — bounds HBM for conditioned joins regardless of input sizes.")

JOIN_MAX_SUBPARTITIONS = int_conf(
    "spark.rapids.sql.join.maxSubPartitions", 64,
    "Upper bound on hash sub-partitions when a join's build side "
    "exceeds the sub-partitioning threshold.")

SEGSUM_BLOCK_ROWS = int_conf(
    "spark.rapids.tpu.segsum.blockRows", 1024,
    "Rows per f32 partial-sum block in the split-f64 segmented sum "
    "(bounds f32 accumulation error; ops/segsum.BLOCK).")

SEGSUM_MAX_PARTIALS = int_conf(
    "spark.rapids.tpu.segsum.maxPartials", 1 << 22,
    "Blocked split-f64 segment sums cap (segments x blocks) at this "
    "many partials; beyond it the guarded unblocked path runs.")

SEGSUM_MATMUL_MAX_SEGMENTS = int_conf(
    "spark.rapids.tpu.segsum.matmulMaxSegments", 32,
    "One-hot MXU matmul partials run for segment counts up to this "
    "(the materialized one-hot costs capacity*segments*4 bytes of HBM "
    "traffic).")

SPLIT_SUM_MAX_ABS = float_conf(
    "spark.rapids.tpu.sum.splitMaxAbs", 1e34,
    "Split-f64 sums reroute to the exact path when any |value| exceeds "
    "this (an f32 block partial could overflow).")

WINDOW_STREAM_TARGET_ROWS = int_conf(
    "spark.rapids.sql.window.streamTargetRows", 0,
    "Target rows per streamed range batch in out-of-core window "
    "evaluation (0 = the largest input run's size).")

BLOOM_DEFAULT_NUM_BITS = int_conf(
    "spark.rapids.tpu.bloomFilter.numBits", 1 << 20,
    "Default bit-array size for build_bloom_filter.")

BLOOM_DEFAULT_NUM_HASHES = int_conf(
    "spark.rapids.tpu.bloomFilter.numHashes", 3,
    "Default hash-function count for build_bloom_filter.")

HEARTBEAT_INTERVAL_S = float_conf(
    "spark.rapids.shuffle.heartbeat.intervalSeconds", 5.0,
    "Executor -> driver shuffle heartbeat period (peer discovery).")

SORT_OOC_THRESHOLD = int_conf(
    "spark.rapids.sql.sort.outOfCoreThresholdBytes", 1 << 30,
    "Multi-batch sorts whose input exceeds this many device bytes merge "
    "OUT OF CORE: each batch sorts on device and demotes to a host run, "
    "sampled key bounds split the key space into ranges, and each range "
    "re-loads + sorts independently — peak HBM is one output range "
    "(GpuSortExec spilled-run merge analog).")

ANSI_ENABLED = bool_conf(
    "spark.sql.ansi.enabled", False,
    "ANSI SQL mode: integral overflow, divide by zero, invalid numeric "
    "casts and out-of-bounds array indexes raise AnsiViolation instead "
    "of wrapping / returning null (reference: GpuCast ansi variants, "
    "CheckOverflow shim rules). Device kernels accumulate a violation "
    "flag per expression site; it rides the collect's packed fetch, so "
    "ANSI checking adds no extra device round trips.")

DPP_ENABLED = bool_conf(
    "spark.rapids.sql.dpp.enabled", True,
    "Dynamic partition pruning: when a broadcast join's probe side scans "
    "a Hive-partitioned source keyed on a partition column, prune the "
    "scan's file list to the build side's distinct key values before "
    "reading (GpuFileSourceScanExec DynamicPruningExpression analog).")

JOIN_DIRECT_TABLE_MULT = int_conf(
    "spark.rapids.tpu.join.directTableMultiplier", 4,
    "Direct-address join fast path: the key-range table is this multiple "
    "of the build side's capacity; build key ranges wider than that fall "
    "back to the sort-based join (speculatively validated).")

SHUFFLE_LOCAL_DEVICE_SPLIT = bool_conf(
    "spark.rapids.shuffle.localDeviceSplit.enabled", True,
    "Single-process repartitions split ON DEVICE into per-partition "
    "masked batches (zero host round trips, zero compaction scatters) "
    "instead of serializing through the shuffle manager. Applies only to "
    "MULTITHREADED mode; ICI and P2P always run their real transports. "
    "Disable to force the file-backed shuffle (manager testing).")

SHUFFLE_MANAGER_MODE = str_conf(
    "spark.rapids.shuffle.mode", "MULTITHREADED",
    "MULTITHREADED (threaded host serialization over local shuffle files), "
    "ICI (collective all-to-all over the device mesh when all partitions "
    "live on one slice), or P2P (cached map output served to peers through "
    "the bounce-buffer transport — the UCX-mode analog).")

P2P_TRANSPORT = str_conf(
    "spark.rapids.shuffle.p2p.transport", "inprocess",
    "P2P shuffle wire: tcp (length-prefixed frames over sockets, the DCN "
    "path) or inprocess (direct calls; single-process and tests).")

P2P_BOUNCE_BUFFER_SIZE = int_conf(
    "spark.rapids.shuffle.p2p.bounceBufferSize", 4 << 20,
    "Bytes per bounce buffer; also the transfer window size.")

P2P_BOUNCE_BUFFERS = int_conf(
    "spark.rapids.shuffle.p2p.bounceBuffers", 4,
    "Bounce buffers per pool (bounds in-flight transfer memory).")

P2P_CACHE_LIMIT = int_conf(
    "spark.rapids.shuffle.p2p.cacheLimitBytes", 1 << 30,
    "Host bytes of cached shuffle blocks before spilling to disk.")

SHUFFLE_MT_WRITER_THREADS = int_conf(
    "spark.rapids.shuffle.multiThreaded.writer.threads", 8,
    "Thread pool size for multithreaded shuffle writes.")

SHUFFLE_MT_READER_THREADS = int_conf(
    "spark.rapids.shuffle.multiThreaded.reader.threads", 8,
    "Thread pool size for multithreaded shuffle reads.")

SHUFFLE_COMPRESSION_CODEC = str_conf(
    "spark.rapids.shuffle.compression.codec", "none",
    "Codec for serialized shuffle batches: none, lz4 (native C++ block "
    "codec), zstd, or zlib. lz4/zstd degrade to zlib when their backend "
    "is unavailable; the resolved codec is what gets recorded on disk.")

# -- streaming ingestion + materialized views (streaming/) -------------------

STREAMING_POOL = str_conf(
    "spark.rapids.streaming.pool", "default",
    "Scheduling pool StreamingQuery micro-batches submit to on the "
    "query service (must name a configured service pool); streams are "
    "recurring tenants, so their pool/tenant SLOs roll up on /slo like "
    "any other traffic.")

STREAMING_TRIGGER_INTERVAL_MS = int_conf(
    "spark.rapids.streaming.triggerIntervalMs", 50,
    "Micro-batch trigger cadence: how long a running stream sleeps "
    "between an empty poll and the next source check.")

STREAMING_MAX_FILES_PER_TRIGGER = int_conf(
    "spark.rapids.streaming.maxFilesPerTrigger", 16,
    "File-watch source batch bound: at most this many newly-seen files "
    "enter one micro-batch; the rest wait for the next trigger.")

STREAMING_MV_INCREMENTAL = bool_conf(
    "spark.rapids.streaming.mv.incremental.enabled", True,
    "Maintain materialized views from the CDF delta (append for "
    "projections/filters, touched-group re-aggregation for "
    "aggregates). Off: every refresh is a full recompute of the "
    "registered plan.")

STREAMING_MV_MAX_TOUCHED_GROUPS = int_conf(
    "spark.rapids.streaming.mv.maxTouchedGroups", 64,
    "Re-aggregation bound: when one refresh's CDF delta touches more "
    "distinct group keys than this, the refresh falls back to a full "
    "recompute instead of building an oversized touched-key filter.")

PARQUET_READER_TYPE = str_conf(
    "spark.rapids.sql.format.parquet.reader.type", "AUTO",
    "PERFILE, COALESCING, MULTITHREADED or AUTO (reference: "
    "GpuParquetScan reader modes).")

MULTITHREADED_READ_NUM_THREADS = int_conf(
    "spark.rapids.sql.multiThreadedRead.numThreads", 20,
    "Thread pool for multithreaded file prefetch.")

READER_COALESCE_TARGET_BYTES = int_conf(
    "spark.rapids.sql.reader.coalescing.targetBytes", 256 << 20,
    "Target bytes when stitching small files/row-groups into one decode.")

HAS_NANS = bool_conf(
    "spark.rapids.sql.hasNans", False,
    "Assume float data may contain NaNs (affects some agg/join support).")

IMPROVED_FLOAT_OPS = bool_conf(
    "spark.rapids.sql.variableFloatAgg.enabled", True,
    "Allow float aggregations whose result may differ in ULPs from CPU "
    "due to parallel reduction order.")

ENABLE_CAST_STRING_TO_TIMESTAMP = bool_conf(
    "spark.rapids.sql.castStringToTimestamp.enabled", False,
    "String->timestamp cast has corner cases; off by default like the "
    "reference.")

DECIMAL_ENABLED = bool_conf(
    "spark.rapids.sql.decimalType.enabled", True,
    "Enable decimal processing on device (int64 unscaled, p<=18).")

TEST_INJECT_RETRY_OOM = str_conf(
    "spark.rapids.sql.test.injectRetryOOM", "",
    "Test-only: 'retry[:N]' or 'split[:N]' to force OOM exceptions on the "
    "Nth device allocation (reference: RmmSpark.forceRetryOOM).",
    internal=True)

TEST_FAULTS = str_conf(
    "spark.rapids.test.faults", "",
    "Test-only fault injection: semicolon-separated "
    "'<point>[@<op>]:<kind>:<prob-or-count>[:<seed>]' entries armed on "
    "the process-wide fault registry at execute() (runtime/faults.py; "
    "the chaos-harness generalization of RmmSpark.forceRetryOOM). "
    "Kinds: oom, crash, fetch, disconnect, corrupt, slow. A value in "
    "(0,1) is a seeded per-hit probability; an integer N fires the "
    "first N hits.", internal=True)

SHUFFLE_FETCH_MAX_RETRIES = int_conf(
    "spark.rapids.shuffle.fetch.maxRetries", 3,
    "Retries per shuffle block fetch before the map output is declared "
    "lost and recomputed from the retained plan lineage.")

SHUFFLE_FETCH_RETRY_WAIT_MS = int_conf(
    "spark.rapids.shuffle.fetch.retryWaitMs", 50,
    "Initial backoff between shuffle fetch retries, in milliseconds.")

SHUFFLE_FETCH_BACKOFF_MULT = float_conf(
    "spark.rapids.shuffle.fetch.backoffMultiplier", 2.0,
    "Multiplier applied to the fetch retry wait after each failed "
    "attempt (exponential backoff).")

SHUFFLE_CONNECT_TIMEOUT_MS = int_conf(
    "spark.rapids.shuffle.fetch.connectTimeoutMs", 30000,
    "Timeout for establishing a transport connection to a shuffle peer; "
    "a timed-out connect counts as a retryable fetch failure against "
    "that peer.")

SHUFFLE_BOUNCE_ACQUIRE_TIMEOUT_MS = int_conf(
    "spark.rapids.shuffle.p2p.bounceAcquireTimeoutMs", 60000,
    "Default timeout waiting for a free bounce buffer; expiry raises a "
    "retryable ShuffleFetchError instead of blocking forever when a "
    "peer dies holding buffers.")

RUNTIME_FALLBACK_ENABLED = bool_conf(
    "spark.rapids.sql.runtimeFallback.enabled", True,
    "Per-operator circuit breaker: after repeated non-OOM device "
    "failures of the same operator the op is runtime-demoted to the CPU "
    "fallback path for the rest of the ENGINE PROCESS — every session "
    "sharing the device sees the demotion, like the speculation "
    "blocklist, since the broken kernel is process-wide state (recorded "
    "as a fallback reason in explain/planVerify). Disable to forbid "
    "demotion — crashes then surface to the caller.")

RUNTIME_FALLBACK_MAX_FAILURES = int_conf(
    "spark.rapids.sql.runtimeFallback.maxFailures", 2,
    "Non-OOM device failures of the same operator before the circuit "
    "breaker demotes it to CPU.")

METRICS_LEVEL = str_conf(
    "spark.rapids.sql.metrics.level", "MODERATE",
    "ESSENTIAL, MODERATE or DEBUG metric collection.")

LORE_DUMP_IDS = str_conf(
    "spark.rapids.sql.lore.idsToDump", "",
    "Comma-separated LORE operator ids (session.last_metrics shows each "
    "operator's id) whose input batches + pickled operator dump to "
    "lore.dumpPath during execution; spark_rapids_tpu.lore.replay() "
    "re-executes one dumped operator, including in a fresh process.")

LORE_DUMP_PATH = str_conf(
    "spark.rapids.sql.lore.dumpPath", "",
    "Directory for LORE dumps (one lore-<id> subdirectory per operator).")

CPU_ORACLE_STRICT = bool_conf(
    "spark.rapids.sql.test.strictOracle", True,
    "Test-only: compare device results bit-for-bit against the CPU path.",
    internal=True)

ADAPTIVE_ENABLED = bool_conf(
    "spark.rapids.sql.adaptive.enabled", True,
    "AQE runtime join-strategy conversion: a join build side whose STATIC "
    "size estimate could not prove it broadcastable is measured at "
    "runtime and converted to a cached broadcast when it lands under "
    "spark.rapids.sql.broadcastSizeBytes (AQE DynamicJoinSelection "
    "analog).")

DELTA_LOW_SHUFFLE_MERGE = bool_conf(
    "spark.rapids.sql.delta.lowShuffleMerge.enabled", True,
    "MERGE rewrites only the TOUCHED ROWS of matched files: matched "
    "target rows die via a deletion vector and updated versions land in "
    "a small new file, so untouched rows of touched files never rewrite "
    "(GpuLowShuffleMergeCommand analog). Disable for full-file "
    "rewrites.")

AQE_SKEW_FACTOR = float_conf(
    "spark.rapids.sql.adaptive.skewJoin.skewedPartitionFactor", 4.0,
    "A reduce partition whose measured map-output bytes exceed this "
    "multiple of the median is counted skewed (skewedPartitions metric; "
    "oversized partitions already split into target-size batches at "
    "read time — AQE OptimizeSkewedJoin's split, measured not guessed).")

AQE_COALESCE_PARTITIONS = bool_conf(
    "spark.rapids.sql.adaptive.coalescePartitions.enabled", True,
    "Adaptive shuffle-partition coalescing from MEASURED map-output "
    "sizes: adjacent undersized reduce partitions merge into shared "
    "output batches at read time (AQE CoalesceShufflePartitions "
    "analog). Note: output batches are then not partition-aligned "
    "(keyed co-location still holds per ROW); disable for consumers "
    "that require one batch per requested partition. Partitions larger than "
    "the batch target still split either way.")

BROADCAST_SIZE_BYTES = int_conf(
    "spark.rapids.sql.broadcastSizeBytes", 10 << 20,
    "Join build sides whose plan-size estimate is at or below this "
    "threshold are broadcast: materialized once through TpuBroadcastExchangeExec "
    "(spillable, reused across replays; replicated across the mesh in "
    "sharded plans) instead of coalesced per-query "
    "(autoBroadcastJoinThreshold analog).", commonly_used=True)

JOIN_SUBPARTITION_BYTES = int_conf(
    "spark.rapids.sql.join.subPartition.targetBytes", 1 << 30,
    "Build sides larger than this sub-partition by Spark-exact key hash "
    "into ceil(size/target) buckets; probe batches split the same way and "
    "bucket pairs join independently with spillable build partitions "
    "(GpuSubPartitionHashJoin analog). 0 disables.")

SPLIT_F64_SUM = str_conf(
    "spark.rapids.tpu.sum.splitF64", "auto",
    "f64 SUM/AVG reduction mode. 'auto': on TPU (where f64 compute is "
    "emulated) run the fast exact hi/lo f32 decomposition with blocked "
    "accumulation (~1e-9 typical relative error; a runtime guard reroutes "
    "to the exact path on huge magnitudes or cancellation). Variance/"
    "stddev MEANS always use the exact path (a mean error amplifies "
    "quadratically in the centered pass); only the positive-valued "
    "centered sums split. CPU backends keep native f64. 'true'/'false' "
    "force the mode. The same trade the reference gates with "
    "variableFloatAgg.enabled.")

KERNELS_SORT_ENABLED = str_conf(
    "spark.rapids.tpu.kernels.sort.enabled", "auto",
    "Pallas multi-column sort kernel (kernels/sort.py): a bitonic "
    "network over packed two-limb key operands + payload permutation "
    "in ONE fused device program, replacing the multi-operand "
    "lexicographic lax.sort. 'auto' enables it on non-CPU backends "
    "(CPU runs Pallas in interpret mode — correct but slow); "
    "'true'/'false' force. Bit-identity with the HLO path is pinned; "
    "ineligible shapes (non-power-of-two capacity, VMEM budget) fall "
    "back per call, and a kernel crash demotes the primitive to HLO "
    "for the process (reason in explain()/event log).")

KERNELS_SEGREDUCE_ENABLED = str_conf(
    "spark.rapids.tpu.kernels.segreduce.enabled", "auto",
    "Pallas segmented-reduction kernels (kernels/segreduce.py): fused "
    "two-limb 64-bit segment min/max (hi-limb reduce + lo-limb "
    "tiebreak in one two-pass program instead of 4+ scatter/gather "
    "passes) and the blocked one-hot split-sum partials built in VMEM "
    "instead of materializing the one-hot in HBM. 'auto'/'true'/"
    "'false' as for kernels.sort.enabled.")

KERNELS_HASHPROBE_ENABLED = str_conf(
    "spark.rapids.tpu.kernels.hashprobe.enabled", "auto",
    "Pallas hash-probe join kernel (kernels/hashprobe.py): a bounded-"
    "attempt open-addressing table over two-limb keys replaces the "
    "dense-code prefix chain (two full sorts) for single-integer-key "
    "joins with unique build keys; duplicate/overflowing builds set a "
    "device flag and the sort-based probe replays (speculation "
    "machinery). 'auto'/'true'/'false' as for kernels.sort.enabled.")

KERNELS_COMPACT_ENABLED = str_conf(
    "spark.rapids.tpu.kernels.compact.enabled", "auto",
    "Pallas row-compaction kernel (kernels/compact.py): one i32 "
    "gather-map scatter + ONE fused kernel gathering every column's "
    "32-bit limb streams, replacing 2-3 scatter passes per 64-bit "
    "column in every filter/join-output/split compaction. "
    "'auto'/'true'/'false' as for kernels.sort.enabled.")

KERNELS_VMEM_BUDGET = int_conf(
    "spark.rapids.tpu.kernels.vmemBudgetBytes", 64 << 20,
    "Per-call VMEM working-set bound for the Pallas kernels: a "
    "primitive whose resident operands would exceed this falls back "
    "to the HLO path for that call (counted as an hloFallback in the "
    "compile metric scope).")

KERNELS_SEGREDUCE_MAX_SEGMENTS = int_conf(
    "spark.rapids.tpu.kernels.segreduce.maxSegments", 8192,
    "Segment-count bound for the Pallas segmented min/max kernel (the "
    "per-block accumulator is segment-sized in VMEM); wider segment "
    "spaces keep the native-32-bit HLO scatter path.")

KERNELS_HASHPROBE_ATTEMPTS = int_conf(
    "spark.rapids.tpu.kernels.hashprobe.attempts", 4,
    "Rehash attempts for the Pallas hash-probe table: build rows that "
    "cannot place within this many alternative slots (or duplicate "
    "build keys) set the failure flag and the join replays on the "
    "sort-based probe.")

AGG_MAX_DICT_GROUPS = int_conf(
    "spark.rapids.tpu.agg.maxDictGroups", 1 << 16,
    "Max key-domain product for the no-sort dictionary-code aggregation "
    "fast path (grouping keys that are dictionary-encoded strings or "
    "booleans aggregate by direct segment reduction, no sort).")

DEVICE_ORDINAL = int_conf(
    "spark.rapids.tpu.deviceOrdinal", -1,
    "Local device the session computes on: -1 = auto (first local "
    "device; multi-process launches pick round-robin by process index, "
    "the GpuDeviceManager executor-id addressing analog). An explicit "
    "ordinal must be a valid jax local device index.", startup_only=True)

AGG_MAX_KEY_DOMAIN_GROUPS = int_conf(
    "spark.rapids.tpu.agg.maxKeyDomainGroups", 1 << 21,
    "Max key-domain product for the no-sort INTEGER-key aggregation fast "
    "path: when every grouping key is an integer-family column whose "
    "(min,max) bound is known from upload-time column statistics, the "
    "group-by runs as a direct segment reduction over the value domain "
    "instead of a full sort. 0 disables. Domains above this (or above "
    "16x the batch capacity) fall back to the sort-segment path.")

AGG_FUSE_INPUT = bool_conf(
    "spark.rapids.tpu.agg.fuseInput", True,
    "Fuse Project/Filter chains feeding an aggregate into the aggregate "
    "kernel: one XLA program evaluates predicates as weight masks (no row "
    "compaction) and value expressions inline (WholeStageCodegen analog).")

SCAN_DEVICE_CACHE = bool_conf(
    "spark.rapids.tpu.scan.deviceCache", True,
    "Cache the uploaded device image of in-memory scan batches on the host "
    "table (GpuInMemoryTableScanExec analog); evicted on device OOM.")

PLAN_VERIFY_MODE = str_conf(
    "spark.rapids.sql.planVerify.mode", "off",
    "Static plan verification of every converted plan before execution "
    "(spark_rapids_tpu.lint): off, warn (print diagnostics and "
    "continue), or error (raise PlanVerificationError). The test suite "
    "runs with error; `python -m spark_rapids_tpu.lint` runs the same "
    "verifier over the TPC-H golden suite plus the registry/repo "
    "audits.", commonly_used=True)


SHAPE_BUCKETS = str_conf(
    "spark.rapids.sql.shapeBuckets", "pow2",
    "Capacity bucket policy for device batches: every batch capacity "
    "rounds UP to the next bucket before any kernel sees it, so the "
    "whole workload compiles to a BOUNDED kernel set instead of one "
    "XLA program per row count (mask-aware execs tolerate the dead "
    "tail rows). 'pow2' (default) and 'pow4' grow geometrically from "
    "shapeBuckets.minBucket; an explicit ascending comma-separated "
    "list (e.g. '1024,16384,262144') declares the exact set, with "
    "pow2 growth above its largest entry. Bucket pad waste is counted "
    "in the `compile` metric scope (padWasteRows). The policy is "
    "PROCESS-WIDE (pushed at query start, like the other tuning "
    "knobs): sessions executing concurrently in one process should "
    "agree on it — a mid-drain policy switch costs extra compiled "
    "shapes, never correctness.", commonly_used=True)

SHAPE_BUCKETS_MIN = int_conf(
    "spark.rapids.sql.shapeBuckets.minBucket", 128,
    "Smallest capacity bucket (and the unit every bucket must be a "
    "multiple of): 128 is the TPU lane width, so buckets tile cleanly "
    "onto the VPU/MXU. Raising it trades pad waste for fewer distinct "
    "compiled shapes on tiny batches.")

EXECUTABLE_CACHE_ENABLED = bool_conf(
    "spark.rapids.sql.executableCache.enabled", True,
    "Cache the converted executable plan (lowered exec tree + "
    "overrides meta) keyed on the literal-stripped structural "
    "fingerprint (plan/fingerprint.py): a repeated query template "
    "skips overrides conversion, plan verification and kernel "
    "re-tracing entirely; distinct-literal variants of one template "
    "share the grouped entry's compiled-kernel set. Entries drop on "
    "warehouse invalidation (writes/commits) and on circuit-breaker "
    "demotions. Hit/miss counters live in the `compile` metric scope.",
    commonly_used=True)

EXECUTABLE_CACHE_MAX_PLANS = int_conf(
    "spark.rapids.sql.executableCache.maxPlans", 64,
    "LRU bound on cached plan TEMPLATES (literal-stripped "
    "fingerprints) in the executable cache. NOTE: a cached tree pins "
    "its plan's in-memory source tables (scan-node references), so "
    "this bound also bounds host memory pinned by the cache — size it "
    "to the serving working set, not to every plan ever seen.")

EXECUTABLE_CACHE_MAX_VARIANTS = int_conf(
    "spark.rapids.sql.executableCache.maxVariantsPerPlan", 4,
    "LRU bound on literal variants retained per cached template: each "
    "variant pins one converted exec tree; template-mates beyond it "
    "still share the template's compiled kernels.")

ASYNC_RESULT_FETCH = bool_conf(
    "spark.rapids.sql.asyncResultFetch", True,
    "Move the final device->host result fetch off the device-semaphore "
    "critical section: the collect's packed d2h kernel is ENQUEUED "
    "under the semaphore, the semaphore releases once the last kernel "
    "is in flight, and the ~0.1s tunnel round trip completes without "
    "blocking the next admitted query (reference: spark-rapids async "
    "d2h pipelining). Per-batch fetches that must validate speculation "
    "flags stay synchronous.")


class RapidsConf:
    """Immutable-ish view over a plain {key: value} dict with typed access."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings = dict(settings or {})

    def get(self, key: str) -> Any:
        entry = _REGISTRY.get(key)
        if key in self._settings:
            raw = self._settings[key]
            return entry.conv(raw) if entry is not None and isinstance(raw, str) else raw
        if entry is None:
            raise KeyError(f"unknown conf key {key}")
        return entry.default

    def get_entry(self, entry: ConfEntry) -> Any:
        return self.get(entry.key)

    def set(self, key: str, value: Any) -> "RapidsConf":
        s = dict(self._settings)
        s[key] = value
        return RapidsConf(s)

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._settings)

    # Convenience accessors used throughout the engine.
    @property
    def sql_enabled(self) -> bool:
        return self.get_entry(SQL_ENABLED)

    @property
    def explain_mode(self) -> str:
        return str(self.get_entry(EXPLAIN)).upper()

    @property
    def is_explain_only(self) -> bool:
        return str(self.get_entry(SQL_MODE)).lower() == "explainonly"

    @property
    def batch_size_bytes(self) -> int:
        return self.get_entry(BATCH_SIZE_BYTES)

    @property
    def concurrent_tpu_tasks(self) -> int:
        return self.get_entry(CONCURRENT_TPU_TASKS)

    def is_op_enabled(self, kind: str, name: str) -> bool:
        key = f"spark.rapids.sql.{kind}.{name}"
        if key in self._settings:
            return _to_bool(self._settings[key])
        entry = _REGISTRY.get(key)
        return bool(entry.default) if entry else True


def registry() -> Dict[str, ConfEntry]:
    return dict(_REGISTRY)


def generate_docs() -> str:
    """Markdown table of all configs (reference: docs/configs.md generation
    from RapidsConf.help)."""
    import importlib
    import pkgutil

    # per-op kill switches, format keys, profiler/filecache/optimizer
    # confs all register at their module's import time; walk the whole
    # package so the doc is complete no matter what the process
    # imported first
    import spark_rapids_tpu
    for _m in pkgutil.walk_packages(spark_rapids_tpu.__path__,
                                    "spark_rapids_tpu."):
        try:
            importlib.import_module(_m.name)
        except Exception:
            pass  # optional backends (pyarrow etc.) may be absent
    lines = [
        "# spark_rapids_tpu configuration",
        "",
        "| Key | Default | Description |",
        "|---|---|---|",
    ]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.internal:
            continue
        lines.append(f"| `{e.key}` | `{e.default}` | {e.doc} |")
    lines += [
        "",
        "## SQL entry point",
        "",
        "`TpuSession.sql(text)` lowers SQL text onto the same plan layer "
        "the DataFrame DSL builds, so every key above — overrides kill "
        "switches, AQE, fallback — applies to SQL queries unchanged. "
        "Temp views registered with `create_or_replace_temp_view` (or "
        "`CREATE TEMP VIEW`) and file-format tables registered via "
        "`CREATE TEMP VIEW v USING fmt OPTIONS (path '...')` resolve "
        "through `session.catalog`; views capture the PLAN, live for the "
        "session, and drop via `DROP VIEW [IF EXISTS]`. The supported "
        "grammar table lives in README.md; `bench.py --sql` and "
        "`scale_test.py --sql` run the TPC-H corpus from SQL text.",
        "",
        "## Static analysis (`python -m spark_rapids_tpu.lint`)",
        "",
        "One CLI runs three tools and exits non-zero on any diagnostic: "
        "a **plan verifier** (walks every converted plan and asserts "
        "schema contracts, device/host transition correctness, exchange "
        "partitioning, decimal precision/scale propagation, TypeSig "
        "conformance and fallback-reason hygiene), a **registry "
        "auditor** (ops/* classes vs overrides registrations, ExprChecks "
        "arity, kill-switch keys, SQL exposure, and drift between this "
        "file / SUPPORTED_OPS.md and their generators — regenerate with "
        "`--write-docs`), and a **repo lint** (no host syncs in execs/ "
        "or ops/ outside `dispatch.host_fetch`, no `jax.numpy` outside "
        "the device layers, no undeclared conf-key string literals, no "
        "wall-clock/unseeded randomness in kernels, no dead lambdas). "
        "`spark.rapids.sql.planVerify.mode` additionally runs the plan "
        "verifier inline on every `TpuSession.execute` (`off` in "
        "production, `error` under the test suite); the CLI also "
        "verifies the TPC-H q1-q22 golden corpus in DSL and SQL form, "
        "with AQE on and off. `--list-rules` prints every rule id.",
        "",
        "## Observability",
        "",
        "`spark.rapids.sql.eventLog.enabled` writes one structured "
        "JSONL record per query under `spark.rapids.sql.eventLog.dir` "
        "(`obs/events.py`): the executed plan tree with TYPED "
        "per-operator metrics (timing/count/bytes at "
        "ESSENTIAL/MODERATE/DEBUG levels — the unified registry in "
        "`obs/metrics.py`, filtered by `spark.rapids.sql.metrics."
        "level`), fallback reasons, circuit-breaker demotions, AQE "
        "conversions, spill/retry/recovery counter deltas, shuffle "
        "bytes per exchange, and query wall/phase times with span "
        "attribution. `spark.rapids.trace.enabled` additionally "
        "collects thread-aware host spans (exec boundaries, h2d/d2h "
        "transfers, shuffle fetch/write/serialize, spill, kernel "
        "dispatch) and exports a Chrome trace-event JSON per query "
        "under `spark.rapids.trace.dir` — load it in Perfetto next to "
        "the Xprof device trace `spark.rapids.profile.enabled` "
        "collects. `bench.py` and `scale_test.py` write event logs by "
        "default; `python -m spark_rapids_tpu.tools profile <log>` "
        "builds the offline report (top operators by self time, "
        "compute/transfer/shuffle/spill breakdown, per-exchange skew, "
        "fallback inventory, >=95% span-attribution contract) and "
        "`... compare A B` diffs two runs per-query/per-operator.",
        "",
        "## Query service",
        "",
        "`spark_rapids_tpu.service.QueryService` is the concurrent "
        "multi-tenant front end over one session: a "
        "`spark.rapids.service.maxConcurrentQueries`-wide worker pool "
        "executes admitted queries concurrently (device residency still "
        "gated by `spark.rapids.sql.concurrentGpuTasks`), with named "
        "scheduling pools (`spark.rapids.service.pools`), per-tenant "
        "weighted fair queueing "
        "(`spark.rapids.service.tenantWeights`), bounded queue depth "
        "with typed rejection + retry-after backpressure "
        "(`spark.rapids.service.queueDepth`), per-query deadlines "
        "(`spark.rapids.service.defaultTimeoutMs` or "
        "`submit(timeout_ms=...)`) enforced cooperatively BETWEEN "
        "batches at every exec boundary (as is "
        "`QueryHandle.cancel()`), and memory-pressure-aware admission "
        "consulting the spill catalog "
        "(`spark.rapids.service.admission.maxDeviceBytes`). "
        "Structurally identical plans under result-identical conf are "
        "served from the plan-fingerprint result cache "
        "(`spark.rapids.service.resultCache.*`), invalidated on "
        "temp-view/catalog mutation, `WriteFiles`, and Delta commits. "
        "Event-log records carry tenant/pool/queue-wait/cache-hit "
        "fields (schema v2); `python -m spark_rapids_tpu.tools "
        "loadtest` and `scale_test.py --concurrency N` drive TPC-H "
        "q1-q22 across simulated tenants, asserting bit-identical "
        "results against serial execution and reporting "
        "throughput/p50/p95 latency, queue wait and cache hit rate.",
        "",
        "## Fault tolerance",
        "",
        "The `spark.rapids.shuffle.fetch.*` keys govern shuffle fetch "
        "retry with exponential backoff and per-peer exclusion; a fetch "
        "that exhausts its retries (or a peer the driver evicts) triggers "
        "lost-map-output RECOMPUTE from the retained plan lineage instead "
        "of query failure. `spark.rapids.sql.runtimeFallback.*` governs "
        "the per-operator circuit breaker: repeated non-OOM device "
        "failures demote the op to the CPU fallback path for the rest of "
        "the engine process (every session sharing the device — the "
        "speculation-blocklist pattern), recorded as a fallback reason "
        "in explain()/planVerify. Fault injection for all of this is "
        "conf-driven "
        "(`spark.rapids.test.faults`, internal) through named fault "
        "points audited by the RL-FAULT-POINT lint rule; "
        "`scale_test.py --chaos` runs TPC-H q1-q22 under a seeded fault "
        "schedule asserting bit-identical results, and the `-m chaos` "
        "pytest slice keeps a small seeded run in tier-1.",
    ]
    return "\n".join(lines) + "\n"
