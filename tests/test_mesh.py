"""Mesh-native distributed execution (the tier-1 multichip slice).

Runs the engine on the virtual 8-device host-platform mesh
(conftest forces --xla_force_host_platform_device_count=8 — the same
substrate MULTICHIP_r06 validates the full corpus on) and pins the
PR's contracts:

* q1/q3/q6 DSL executed mesh-native are BIT-IDENTICAL to single-chip;
* q7 (repartition+agg class) lowers every shuffle exchange to the ICI
  collective — hostShuffleFallbacks=0 — and the warm path performs
  ZERO host->device uploads between exchanges (meshHostUploads);
* repeated exchanges over one string dictionary pay the replicated
  byte-matrix upload ONCE (interned by dictionary identity);
* an ICI-requested exchange that must demote (partition count wider
  than the mesh) surfaces its reason in explain()/describe() and still
  returns correct results through the host shuffle;
* the executable cache is mesh-generation-stamped: a tree cached
  before a mesh reconfiguration can neither serve nor re-park after
  it; the plan fingerprint folds the mesh identity token.
"""

import pytest

pytestmark = pytest.mark.multichip


@pytest.fixture(scope="module")
def tables():
    from spark_rapids_tpu.datagen import scale_test_specs
    sf = 0.01
    return {name: spec.generate_table(sf, seed=3)
            for name, spec in scale_test_specs(sf).items()}


@pytest.fixture(scope="module")
def chip_session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession()


@pytest.fixture(scope="module")
def mesh_session():
    from spark_rapids_tpu.session import TpuSession
    return TpuSession({"spark.rapids.mesh.enabled": "true"})


def _mesh_scope():
    from spark_rapids_tpu.obs.metrics import scopes_snapshot
    return dict(scopes_snapshot().get("mesh", {}))


def _delta(before, after):
    return {k: after.get(k, 0) - before.get(k, 0)
            for k in set(after) | set(before)
            if after.get(k, 0) != before.get(k, 0)}


def _walk_execs(node):
    yield node
    for c in getattr(node, "children", ()):
        yield from _walk_execs(c)
    for attr in ("source", "tpu_exec", "cpu_node"):
        nxt = getattr(node, attr, None)
        if nxt is not None:
            yield from _walk_execs(nxt)


def test_mesh_q1_q3_q6_bit_identical(tables, chip_session, mesh_session):
    """The corpus slice: scan->filter->agg (q1), join->agg (q3) and a
    window rank (q6) executed mesh-native match single-chip execution
    bit for bit (the scale_test --mesh contract, in tier-1 form)."""
    import scale_test as ST
    chip_q = ST.build_queries(chip_session, tables)
    mesh_q = ST.build_queries(mesh_session, tables)
    before = _mesh_scope()
    for name in ("q1", "q3", "q6"):
        expected = chip_q[name]().collect_table()
        got = mesh_q[name]().collect_table()
        diff = ST.tables_differ(expected, got)
        assert diff is None, f"{name} diverged on the mesh: {diff}"
    # the mesh actually engaged: scans landed per-device shards
    assert _delta(before, _mesh_scope()).get("shardsDispatched", 0) > 0


def test_mesh_q7_every_exchange_ici_and_warm_uploads_zero(
        tables, chip_session, mesh_session):
    """The q7 repartition+agg acceptance class: every shuffle exchange
    lowers to the ICI all-to-all (no host-shuffle fallback) and the
    WARM path pays zero host->device transfers between exchanges —
    shards are device-resident from the (cached) scan through the
    collective (PERF.md: mid-pipeline uploads are the dominant
    distributed cost class)."""
    import scale_test as ST
    chip_q = ST.build_queries(chip_session, tables)
    mesh_q = ST.build_queries(mesh_session, tables)
    expected = chip_q["q7"]().collect_table()
    got = mesh_q["q7"]().collect_table()  # cold: compiles + shard upload
    assert ST.tables_differ(expected, got) is None
    before = _mesh_scope()
    warm = mesh_q["q7"]().collect_table()
    assert ST.tables_differ(expected, warm) is None
    d = _delta(before, _mesh_scope())
    assert d.get("iciExchanges", 0) >= 1, d
    assert d.get("hostShuffleFallbacks", 0) == 0, d
    assert d.get("meshHostUploads", 0) == 0, \
        f"warm mesh path paid host uploads: {d}"


def test_mesh_string_dict_interned_across_exchanges(tables, mesh_session):
    """String partition keys hash via a byte matrix replicated across
    the mesh; repeated exchanges over ONE dictionary (the cached scan's)
    pay that replication upload once — the dispatch.device_const
    pattern lifted to the mesh (pinned by the upload counter)."""
    import scale_test as ST

    # q7's shape on purpose: its string-keyed exchange is already
    # compiled by the test above, so this pins ONLY the intern behavior
    df = ST.build_queries(mesh_session, tables)["q7"]
    df().collect_table()  # cold for this test: interns the dictionary
    before = _mesh_scope()
    df().collect_table()
    d = _delta(before, _mesh_scope())
    assert d.get("iciExchanges", 0) >= 1, d
    assert d.get("meshDictInterns", 0) == 0, \
        f"re-exchange re-replicated an interned dictionary: {d}"
    assert d.get("meshHostUploads", 0) == 0, d


def test_mesh_exchange_demotion_reason_surfaced(tables, mesh_session):
    """Partition count wider than the mesh: the ICI-requested exchange
    demotes to the host-file shuffle WITH the reason surfaced in the
    exec's describe() and counted in hostShuffleFallbacks — and the
    host path still consumes the sharded scan correctly (to_host is a
    sanctioned gather)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.execs.exchange import TpuShuffleExchangeExec
    from spark_rapids_tpu.plan import from_host_table

    before = _mesh_scope()
    got = (from_host_table(tables["customer"], mesh_session)
           .repartition(16, "c_nationkey")
           .group_by("c_nationkey")
           .agg(F.count("c_custkey").alias("n"))
           .collect_table())
    assert got.num_rows > 0
    d = _delta(before, _mesh_scope())
    assert d.get("hostShuffleFallbacks", 0) >= 1, d
    exchanges = [e for e in _walk_execs(mesh_session._last_executable)
                 if isinstance(e, TpuShuffleExchangeExec)]
    assert exchanges and exchanges[0].ici_fallback_reason
    assert "exceeds" in exchanges[0].ici_fallback_reason
    assert "hostShuffleFallback" in exchanges[0].describe()
    # the overrides tagger surfaces the SAME static reason in explain()
    note_lines = [ln for ln in mesh_session._last_meta.explain().splitlines()
                  if "host-shuffle fallback" in ln]
    assert note_lines and "exceeds" in note_lines[0]


def test_explain_before_first_execute_sees_this_confs_mesh(
        tables, chip_session):
    """explain() must report the demotion reasons the exec will act on
    even BEFORE the session's first execute: explain_plan/apply_overrides
    realize the conf's mesh themselves rather than reading whatever a
    previous session left configured."""
    from spark_rapids_tpu.overrides import explain_plan
    from spark_rapids_tpu.plan import from_host_table
    from spark_rapids_tpu.session import TpuSession

    # leave the process-wide mesh OFF (a stale state for the new session)
    chip_session.placement.prepare()
    fresh = TpuSession({"spark.rapids.mesh.enabled": "true"})
    plan = from_host_table(tables["customer"], fresh).repartition(
        16, "c_nationkey").plan
    out = explain_plan(plan, fresh.conf)
    assert "host-shuffle fallback" in out and "exceeds" in out
    chip_session.placement.prepare()


def test_executable_cache_is_mesh_generation_stamped(tables):
    """A converted tree cached under one mesh config can neither SERVE
    nor RE-PARK after a mesh reconfiguration — even when the plan
    fingerprint comes back around (off -> on -> off), the generation
    stamp keeps the pre-reconfiguration tree out."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.plan import from_host_table
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession()

    def q():
        return (from_host_table(tables["customer"], s)
                .group_by("c_nationkey")
                .agg(F.count("c_custkey").alias("n")))

    q().collect_table()
    assert s.last_executable_cache_hit is False
    q().collect_table()
    assert s.last_executable_cache_hit is True

    # reconfigure the mesh (off -> on -> off): the fingerprint is back
    # to the original, but both cached generations are now stale
    from spark_rapids_tpu.parallel.mesh import MESH
    gen0 = MESH.generation()
    mesh_s = TpuSession({"spark.rapids.mesh.enabled": "true"})
    mesh_s.placement.prepare()
    s.placement.prepare()
    assert MESH.generation() >= gen0 + 2
    q().collect_table()
    assert s.last_executable_cache_hit is False, \
        "a pre-reconfiguration tree served after the mesh changed"
    # the fresh tree parks under the NEW generation and serves again
    q().collect_table()
    assert s.last_executable_cache_hit is True


def test_checked_out_tree_cannot_repark_across_reconfiguration(tables):
    """The release half of the stamp: a token checked out BEFORE a mesh
    reconfiguration must not re-park its tree afterwards (the tree's
    cached device tables reference the old placement)."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.overrides import apply_overrides
    from spark_rapids_tpu.plan import from_host_table
    from spark_rapids_tpu.plan.executable_cache import ExecutableCache
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession()
    s.placement.prepare()
    plan = (from_host_table(tables["customer"], s)
            .group_by("c_nationkey")
            .agg(F.count("c_custkey").alias("n")).plan)
    cache = ExecutableCache()
    tok = cache.checkout(plan, s.conf)
    assert not tok.hit
    executable, meta = apply_overrides(plan, s.conf)

    # mesh reconfigures while the tree is checked out
    mesh_s = TpuSession({"spark.rapids.mesh.enabled": "true"})
    mesh_s.placement.prepare()
    s.placement.prepare()

    tok.fill(executable, meta)
    tok2 = cache.checkout(plan, s.conf)
    assert not tok2.hit, \
        "a tree checked out before a mesh reconfiguration re-parked"


def test_fingerprint_folds_mesh_identity(tables):
    """Plans fingerprinted under different mesh configs never collide:
    the ACTIVE mesh identity token (shape/axes/device ids) folds into
    the fingerprint beyond the conf keys."""
    from spark_rapids_tpu import functions as F
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.plan import from_host_table
    from spark_rapids_tpu.plan.fingerprint import fingerprint
    from spark_rapids_tpu.session import TpuSession

    s = TpuSession()
    plan = (from_host_table(tables["customer"], s)
            .group_by("c_nationkey")
            .agg(F.count("c_custkey").alias("n")).plan)
    s.placement.prepare()
    assert MESH.identity_token() == "mesh:off"
    fp_off = fingerprint(plan, s.conf)

    mesh_s = TpuSession({"spark.rapids.mesh.enabled": "true"})
    mesh_s.placement.prepare()
    tok_8 = MESH.identity_token()
    assert tok_8.startswith("mesh:8/")
    fp_on = fingerprint(plan, mesh_s.conf)
    assert fp_on != fp_off

    hier = TpuSession({"spark.rapids.mesh.enabled": "true",
                       "spark.rapids.mesh.shape": "2x4"})
    hier.placement.prepare()
    assert MESH.identity_token().startswith("mesh:2x4/")
    assert MESH.row_axes() == ("dcn", "ici")
    assert fingerprint(plan, hier.conf) not in (fp_off, fp_on)

    # leave the process-wide mesh OFF for the rest of the suite
    s.placement.prepare()


def test_unstamped_scan_never_lands_sharded(tables):
    """Sharded placement is bound at CONVERSION, not read from process
    state at execute: a tree converted with the mesh off carries no
    re-land boundaries, so its scans must land single-device even when
    a concurrent session flips the process mesh on mid-query (sharded
    input would let GSPMD repartition a wide float kernel and change
    accumulation order). insert_mesh_relands stamps scans with the
    conversion-time generation; unstamped or stale-stamped scans land
    safe."""
    from spark_rapids_tpu.execs.basic import TpuScanExec
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.session import TpuSession

    mesh_s = TpuSession({"spark.rapids.mesh.enabled": "true"})
    off_s = TpuSession()
    try:
        mesh_s.placement.prepare()  # the "concurrent session" flips mesh on
        scan = TpuScanExec([tables["customer"]], device_cache=False)
        assert all(not b.physically_sharded() for b in scan.execute()), \
            "an unstamped (mesh-off-converted) scan landed sharded"
        scan._mesh_scan_gen = MESH.generation()  # conversion-time stamp
        assert any(b.physically_sharded() for b in scan.execute())
        scan._mesh_scan_gen = MESH.generation() - 1  # stale stamp
        assert all(not b.physically_sharded() for b in scan.execute())
    finally:
        off_s.placement.prepare()


def test_backend_reinit_rebuilds_mesh():
    """Device-loss recovery replaces every jax Device object but leaves
    the mesh conf — and the device IDS the identity token hashes —
    unchanged. configure() folds HEALTH's backend generation into its
    config key, so the next prepare() rebuilds the mesh instead of
    serving Device objects from the dead backend."""
    from spark_rapids_tpu.parallel.mesh import MESH
    from spark_rapids_tpu.runtime.health import HEALTH
    from spark_rapids_tpu.session import TpuSession

    mesh_s = TpuSession({"spark.rapids.mesh.enabled": "true"})
    off_s = TpuSession()
    try:
        mesh_s.placement.prepare()
        m1, g1 = MESH.mesh(), MESH.generation()
        assert m1 is not None
        mesh_s.placement.prepare()  # unchanged conf + backend: no-op
        assert MESH.mesh() is m1 and MESH.generation() == g1
        with HEALTH._lock:  # what a device-loss reinit does
            HEALTH._generation += 1
        mesh_s.placement.prepare()
        # the mesh was REBUILT from the (re-discovered) backend: the
        # generation bumps, staling every cached placement. (jax
        # interns Mesh by (devices, axes), so with the simulated — not
        # real — reinit the rebuilt object may compare identical; the
        # generation is the observable coherency contract.)
        assert MESH.generation() > g1, \
            "mesh built from the dead backend survived the reinit"
    finally:
        off_s.placement.prepare()


def test_clear_mesh_caches_drops_interned_device_state():
    """The mesh-exchange caches (interned replicated dictionary
    matrices, MeshExchange instances with their jitted programs) key on
    device IDS, which survive a device-loss backend reinit unchanged —
    so device-loss recovery (runtime/health.py) and the OOM eviction
    path (runtime/retry.py) clear them through clear_mesh_caches like
    every other device-referencing cache."""
    import jax
    import numpy as np
    from spark_rapids_tpu.parallel import exchange as EX

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("data",))
    d = np.array(["aa", "b"])
    EX.interned_dict_bytes(d, mesh)
    with EX._DICT_INTERN_LOCK:
        assert EX._DICT_INTERN
    assert EX.clear_mesh_caches() >= 1
    with EX._DICT_INTERN_LOCK:
        assert not EX._DICT_INTERN
    assert not EX.MeshExchange._cache


def test_dict_intern_single_upload_under_concurrency(monkeypatch):
    """Two workers first-exchanging over ONE dictionary concurrently
    (QueryService pattern) pay the replication upload once: the
    in-flight marker makes the loser wait for the winner's interned
    entry instead of racing a second device_put — the warm-path-zero
    meshHostUploads contract must hold under concurrency too."""
    import threading
    import time

    import jax
    import numpy as np
    from spark_rapids_tpu.parallel import exchange as EX

    EX.clear_mesh_caches()
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("data",))
    d = np.array(["x", "yy", "zzz"])
    real = EX.string_dict_bytes

    def slow(dictionary, *a, **k):  # widen the in-flight window
        time.sleep(0.05)
        return real(dictionary, *a, **k)

    monkeypatch.setattr(EX, "string_dict_bytes", slow)
    before = _mesh_scope()
    results = []
    threads = [threading.Thread(
        target=lambda: results.append(EX.interned_dict_bytes(d, mesh)))
        for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    delta = _delta(before, _mesh_scope())
    assert delta.get("meshDictInterns", 0) == 1, delta
    assert delta.get("meshHostUploads", 0) == 2, delta
    assert results[0][0] is results[1][0]  # one canonical device entry
    EX.clear_mesh_caches()


def test_mesh_shape_validation():
    """Malformed or oversized spark.rapids.mesh.shape raises typed."""
    from spark_rapids_tpu.errors import ColumnarProcessingError
    from spark_rapids_tpu.parallel.mesh import _parse_shape

    assert _parse_shape("", 8) == (8,)
    assert _parse_shape("4", 8) == (4,)
    assert _parse_shape("2x4", 8) == (2, 4)
    with pytest.raises(ColumnarProcessingError):
        _parse_shape("banana", 8)
    with pytest.raises(ColumnarProcessingError):
        _parse_shape("2x2x2", 8)
    with pytest.raises(ColumnarProcessingError):
        _parse_shape("16", 8)
