"""TPU physical operators (reference: the ~35 GpuExec operators, SURVEY.md
§2.3). Each exec consumes/produces DeviceTable batches; expression work is
fused into single jitted XLA computations via ops/expr.py."""

from spark_rapids_tpu.execs.base import TpuExec, HostToDevice, DeviceToHost, InputAdapter  # noqa: F401
from spark_rapids_tpu.execs.basic import (  # noqa: F401
    TpuFileScanExec,
    TpuScanExec,
    TpuRangeExec,
    TpuProjectExec,
    TpuFilterExec,
    TpuLimitExec,
    TpuUnionExec,
    TpuCoalesceExec,
    TpuExpandExec,
)
from spark_rapids_tpu.execs.aggregate import TpuHashAggregateExec  # noqa: F401
from spark_rapids_tpu.execs.sort import TpuSortExec  # noqa: F401
