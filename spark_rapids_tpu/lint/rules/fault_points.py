"""RL-FAULT-POINT — the chaos harness's fault-point registry
(runtime/faults.FAULT_POINTS) and the ``fault_point("<name>")`` call
sites must agree in both directions: every registered point names an
existing site in its registered module, every site uses a registered
name, and names are string literals (a computed name would dodge the
audit)."""

from __future__ import annotations

import ast
from typing import List

from spark_rapids_tpu.lint.diagnostics import Diagnostic, make
from spark_rapids_tpu.lint.rules.common import _attr_chain


def _is_fault_point_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = _attr_chain(node.func)
    return chain == "fault_point" or chain.endswith(".fault_point")


def _check_fault_sites(rel: str, tree: ast.AST, calls,
                       diags: List[Diagnostic]):
    """Per-file half of RL-FAULT-POINT: record every fault_point call
    into ``calls`` (name -> [file:line]) and flag non-literal or
    unregistered names at the site."""
    from spark_rapids_tpu.runtime.faults import FAULT_POINTS
    for node in ast.walk(tree):
        if not _is_fault_point_call(node):
            continue
        arg = node.args[0] if node.args else None
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            diags.append(make(
                "RL-FAULT-POINT", f"{rel}:{node.lineno}",
                "fault_point() name must be a string literal so the "
                "registry audit can see it"))
            continue
        name = arg.value
        if name not in FAULT_POINTS:
            diags.append(make(
                "RL-FAULT-POINT", f"{rel}:{node.lineno}",
                f"fault_point({name!r}) is not registered in "
                "runtime/faults.FAULT_POINTS"))
            continue
        calls.setdefault(name, []).append(f"{rel}:{node.lineno}")


def _check_fault_registry(calls, diags: List[Diagnostic]):
    """Cross-file half of RL-FAULT-POINT: every registered point must
    name at least one existing call site, and a site must live in the
    module the registry claims hosts it (stale registry entries would
    otherwise advertise injectable faults that never fire)."""
    from spark_rapids_tpu.runtime.faults import FAULT_POINTS
    for name, (module, _doc) in sorted(FAULT_POINTS.items()):
        sites = calls.get(name, [])
        if not sites:
            diags.append(make(
                "RL-FAULT-POINT", f"faults.FAULT_POINTS[{name!r}]",
                f"registered fault point has no fault_point({name!r}) "
                "call site anywhere in the repo"))
        elif not any(s.rsplit(":", 1)[0] == module for s in sites):
            diags.append(make(
                "RL-FAULT-POINT", f"faults.FAULT_POINTS[{name!r}]",
                f"no call site in the registered module {module} "
                f"(found: {', '.join(sites)})"))
