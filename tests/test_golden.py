"""Golden-vector oracle: every vector runs through BOTH engine paths (pure
CPU, and the TPU overrides path) and compares against the PINNED expected
values — not against each other (de-circularized oracle, VERDICT r1)."""

import math

import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.columnar import HostColumn, HostTable
from spark_rapids_tpu.ops.expr import col, lit
from spark_rapids_tpu.plan import from_host_table

from tests.golden_vectors import TYPES, VECTORS


def _table(columns, rows):
    names = list(columns.keys())
    cols = []
    for i, (n, tname) in enumerate(columns.items()):
        vals = [r[i] for r in rows]
        cols.append(HostColumn.from_pylist(vals, TYPES[tname]))
    return HostTable(names, cols)


def _values_equal(got, want):
    if want is None or got is None:
        return got is None and want is None
    if isinstance(want, float):
        if math.isnan(want):
            return isinstance(got, float) and math.isnan(got)
        return got == want and (math.copysign(1, got) == math.copysign(1, want)
                                if want == 0 else True)
    return got == want and type(got) is not bool or (got is want)


def _check(got_col, expected, name, path):
    assert len(got_col) == len(expected), (name, path)
    for i, (g, w) in enumerate(zip(got_col, expected)):
        if w is None:
            assert g is None, f"{name}[{i}] {path}: got {g!r}, want null"
        elif isinstance(w, float) and math.isnan(w):
            assert isinstance(g, float) and math.isnan(g), \
                f"{name}[{i}] {path}: got {g!r}, want NaN"
        elif isinstance(w, bool):
            assert g == w and isinstance(g, bool), \
                f"{name}[{i}] {path}: got {g!r}, want {w!r}"
        else:
            assert g == w, f"{name}[{i}] {path}: got {g!r}, want {w!r}"


@pytest.mark.parametrize("vec", VECTORS, ids=[v[0] for v in VECTORS])
def test_golden_vector(vec, session, cpu_session):
    name, columns, rows, build, expected = vec
    table = _table(columns, rows)
    expr = build(F, col, lit).alias("out")

    cpu_out = (from_host_table(table, cpu_session)
               .select(expr).collect_table().columns[0].to_pylist())
    _check(cpu_out, expected, name, "cpu-path")

    tpu_out = (from_host_table(table, session)
               .select(expr).collect_table().columns[0].to_pylist())
    _check(tpu_out, expected, name, "tpu-path")
