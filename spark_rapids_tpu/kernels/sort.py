"""Pallas multi-column sort: a bitonic network over packed key limbs.

The HLO path (``jax.lax.sort`` with the ops/ordering.py operand
decomposition) already avoids emulated 64-bit COMPARES, but XLA still
materializes every operand between comparator stages in HBM. This
kernel runs the whole bitonic network over all key operands + the
payload in ONE fused program: operands stay resident (VMEM within the
``spark.rapids.tpu.kernels.vmemBudgetBytes`` envelope), each
compare-exchange is a vectorized lexicographic compare over the ≤32-bit
limb tuple, and the payload permutation rides the same swaps — no
per-stage HBM round trips and no separate gather pass.

Bit-identity with ``lax.sort``: callers pass a UNIQUE i32 row-index
iota as the payload (ops/ordering.lex_sort contract). The kernel sorts
with the payload as the FINAL tiebreak key, which makes every row tuple
unique — and a total-order bitonic sort of unique tuples produces
exactly the stable sort lax.sort defines. Shapes outside the envelope
(non-power-of-two capacity, >32-bit operands, over-budget working sets)
raise :class:`~spark_rapids_tpu.kernels.KernelIneligible` and the call
falls back to lax.sort.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from spark_rapids_tpu.kernels import KernelIneligible, config, interpret_mode
from spark_rapids_tpu.runtime.faults import fault_point


def _lex_cmp(a_list, b_list):
    """(a > b, a == b) over the lexicographic operand tuple."""
    gt = None
    eq = None
    for a, b in zip(a_list, b_list):
        g = a > b
        e = a == b
        gt = g if gt is None else gt | (eq & g)
        eq = e if eq is None else eq & e
    return gt, eq


def _substage(arrs, n, k, j):
    """One compare-exchange substage of the bitonic network: partner
    distance d = 2^j inside (ascending/descending alternating) blocks
    of 2^k elements. Element i pairs with i^d via the (n/2d, 2, d)
    reshape; the direction bit of the pair is bit (k-j-1) of the major
    index."""
    d = 1 << j
    half = n // (2 * d)
    r = jax.lax.broadcasted_iota(jnp.int32, (half, d), 0)
    asc = ((r >> (k - j - 1)) & 1) == 0
    a_list, b_list = [], []
    for x in arrs:
        xr = x.reshape(half, 2, d)
        a_list.append(xr[:, 0, :])
        b_list.append(xr[:, 1, :])
    gt, eq = _lex_cmp(a_list, b_list)
    swap = jnp.where(asc, gt, (~gt) & (~eq))
    out = []
    for a, b in zip(a_list, b_list):
        na = jnp.where(swap, b, a)
        nb = jnp.where(swap, a, b)
        out.append(jnp.stack([na, nb], axis=1).reshape(n))
    return out


def _build(n: int, dtypes):
    log2n = n.bit_length() - 1
    n_arr = len(dtypes)

    def kernel(*refs):
        arrs = [refs[i][:] for i in range(n_arr)]
        for k in range(1, log2n + 1):
            for j in range(k - 1, -1, -1):
                arrs = _substage(arrs, n, k, j)
        for i, x in enumerate(arrs):
            refs[n_arr + i][:] = x

    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((n,), dt) for dt in dtypes],
        interpret=interpret_mode())


def sort_with_payload(operands: List[jax.Array],
                      payload: jax.Array) -> List[jax.Array]:
    """``lax.sort(operands + [payload], num_keys=len(operands))``,
    fused. ``payload`` must be a unique i32 iota (see module doc)."""
    fault_point("kernels.sort")
    arrs = list(operands) + [payload]
    n = payload.shape[0]
    if n < 2 or (n & (n - 1)) != 0:
        raise KernelIneligible(f"capacity {n} is not a power of two")
    for a in arrs:
        if getattr(a, "ndim", 1) != 1:
            raise KernelIneligible("sort operands must be 1-D")
        if a.dtype.itemsize > 4:
            raise KernelIneligible(f"operand dtype {a.dtype} is wider "
                                   "than one 32-bit limb")
    # in + out + one compare-exchange working copy
    if 3 * sum(a.dtype.itemsize * n for a in arrs) > config().vmem_budget:
        raise KernelIneligible("sort working set exceeds the VMEM budget")
    from spark_rapids_tpu.dispatch import pallas_program
    key = ("sort", n, tuple(str(a.dtype) for a in arrs))
    fn = pallas_program(key, lambda: _build(n, [a.dtype for a in arrs]))
    return list(fn(*arrs))
