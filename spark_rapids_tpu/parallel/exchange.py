"""ICI shuffle exchange: hash-partition rows across a device mesh with ONE
all-to-all collective.

Reference mapping (SURVEY.md §2.6): GpuShuffleExchangeExec's UCX fast path
becomes ``jax.lax.all_to_all`` over the mesh axis — each device bucketizes
its row shard by Spark-exact murmur3 target, pads buckets to the static
shard size, and the collective delivers every device its partition. All
shapes are static (bucket = local shard capacity, the worst case); validity
masks carry the live counts. The plan-integrated entry point is
``MeshExchange`` (used by TpuShuffleExchangeExec when
spark.rapids.shuffle.mode=ICI and the partition count fits the mesh);
the host-file shuffle covers every other case.

String keys hash by their dictionary BYTE matrix (replicated across the
mesh — O(dict) bytes), so Spark-exact murmur3 applies to strings too.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
from spark_rapids_tpu.dispatch import tpu_jit
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import types as T
from spark_rapids_tpu.shuffle.hashing import (
    SPARK_SEED,
    murmur3_hash_device,
    string_dict_bytes,
)


def _shard_map():
    from spark_rapids_tpu.shims import get_shim
    return get_shim().shard_map()


def _bucketize(pid, live, ndev: int, cap: int):
    """Per-row scatter target into a (ndev*cap) padded send buffer:
    pid*cap + rank-within-bucket; dead rows drop."""
    spid = jnp.where(live, pid, ndev)
    order = jnp.argsort(spid, stable=True)
    sorted_pid = spid[order]
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_first = jnp.concatenate([jnp.ones(1, jnp.bool_),
                                sorted_pid[1:] != sorted_pid[:-1]])
    run_start = jnp.where(is_first, idx, 0)
    run_start = jax.lax.associative_scan(jnp.maximum, run_start)
    slot_sorted = idx - run_start
    slot = jnp.zeros(cap, jnp.int32).at[order].set(slot_sorted)
    return jnp.where(live, pid * cap + slot, ndev * cap)


class MeshExchange:
    """Plan-integrated all-to-all exchange over a device mesh.

    One instance is built per (mesh, column dtypes, key layout) — the
    jitted shard_map program is cached on the instance. ``run`` takes the
    coalesced input table's column arrays plus the live-row mask and
    returns, per partition, front-compacted output arrays + live counts.
    """

    _cache: Dict[tuple, "MeshExchange"] = {}

    @classmethod
    def get(cls, mesh, col_dtypes: Tuple[str, ...], key_cols: Tuple[int, ...],
            key_dtypes, string_key_shapes: tuple, cap: int,
            axis_name: str = "data"):
        dev_ids = tuple(d.id for d in np.asarray(mesh.devices).flat)
        key = (dev_ids, col_dtypes, key_cols, tuple(map(str, key_dtypes)),
               string_key_shapes, cap, axis_name)
        inst = cls._cache.get(key)
        if inst is None:
            inst = cls(mesh, key_dtypes, axis_name)
            cls._cache[key] = inst
        return inst

    def __init__(self, mesh, key_dtypes, axis_name: str = "data"):
        self.mesh = mesh
        self.axis_name = axis_name
        self.ndev = mesh.shape[axis_name]
        self.key_dtypes = list(key_dtypes)
        self._fn = None

    def _build(self, ncols: int, nkeys: int, has_sbytes: Tuple[bool, ...]):
        from jax.sharding import PartitionSpec as P_

        ndev = self.ndev
        axis = self.axis_name
        key_dts = self.key_dtypes

        def shard_fn(*flat):
            pos = 0
            datas = flat[pos:pos + ncols]; pos += ncols
            valids = flat[pos:pos + ncols]; pos += ncols
            kdatas = flat[pos:pos + nkeys]; pos += nkeys
            kvalids = flat[pos:pos + nkeys]; pos += nkeys
            live = flat[pos]; pos += 1
            sbytes = {}
            for i, has in enumerate(has_sbytes):
                if has:
                    sbytes[i] = (flat[pos], flat[pos + 1])
                    pos += 2
            cap = datas[0].shape[0] if datas else kdatas[0].shape[0]

            keys = [(kdatas[i], kvalids[i], key_dts[i]) for i in range(nkeys)]
            h = murmur3_hash_device(keys, SPARK_SEED, sbytes)
            pid = h % jnp.int32(ndev)
            pid = jnp.where(pid < 0, pid + ndev, pid)
            tgt = _bucketize(pid, live, ndev, cap)

            send_live = jnp.zeros((ndev * cap,), jnp.bool_).at[tgt].set(
                True, mode="drop").reshape(ndev, cap)
            recv_live = jax.lax.all_to_all(send_live, axis, 0, 0)

            out_datas, out_valids = [], []
            for d, v in zip(datas, valids):
                send = jnp.zeros((ndev * cap,), d.dtype).at[tgt].set(
                    d, mode="drop").reshape(ndev, cap)
                send_v = jnp.zeros((ndev * cap,), jnp.bool_).at[tgt].set(
                    v, mode="drop").reshape(ndev, cap)
                out_datas.append(jax.lax.all_to_all(
                    send, axis, 0, 0).reshape(ndev * cap))
                out_valids.append(jax.lax.all_to_all(
                    send_v, axis, 0, 0).reshape(ndev * cap))

            # per-shard compaction: received blocks are front-compacted per
            # source device but gapped between blocks; one scatter compacts
            # the whole shard and counts the live rows
            flat_live = recv_live.reshape(ndev * cap)
            cpos = jnp.cumsum(flat_live.astype(jnp.int32)) - 1
            ctgt = jnp.where(flat_live, cpos, ndev * cap)
            n_live = jnp.sum(flat_live.astype(jnp.int32))
            comp_d, comp_v = [], []
            for d, v in zip(out_datas, out_valids):
                comp_d.append(jnp.zeros_like(d).at[ctgt].set(d, mode="drop"))
                comp_v.append(jnp.zeros_like(v).at[ctgt].set(v, mode="drop"))
            return tuple(comp_d) + tuple(comp_v) + (n_live[None],)

        n_row_args = 2 * ncols + 2 * nkeys + 1
        in_specs = [P_(axis)] * n_row_args
        for has in has_sbytes:
            if has:
                in_specs += [P_(), P_()]  # replicated dictionary bytes
        out_specs = [P_(axis)] * (2 * ncols) + [P_(axis)]
        sm = _shard_map()
        return tpu_jit(sm(shard_fn, mesh=self.mesh,
                          in_specs=tuple(in_specs),
                          out_specs=tuple(out_specs)))

    def run(self, datas, valids, key_datas, key_valids, live,
            string_bytes: Optional[Dict[int, tuple]] = None):
        """All arrays are GLOBAL row arrays (length divisible by the mesh
        size). Returns (out_datas, out_valids, counts) where each output is
        global with per-device shards front-compacted and ``counts`` holds
        one live count per partition."""
        from jax.sharding import NamedSharding, PartitionSpec as P_

        string_bytes = string_bytes or {}
        has_sbytes = tuple(i in string_bytes for i in range(len(key_datas)))
        if self._fn is None:
            self._fn = self._build(len(datas), len(key_datas), has_sbytes)
        sharding = NamedSharding(self.mesh, P_(self.axis_name))
        rep = NamedSharding(self.mesh, P_())
        flat = [jax.device_put(x, sharding)
                for x in (*datas, *valids, *key_datas, *key_valids, live)]
        for i, has in enumerate(has_sbytes):
            if has:
                mat, lens = string_bytes[i]
                flat.append(jax.device_put(mat, rep))
                flat.append(jax.device_put(lens, rep))
        out = self._fn(*flat)
        ncols = len(datas)
        return (list(out[:ncols]), list(out[ncols:2 * ncols]),
                np.asarray(out[2 * ncols]))


def mesh_hash_exchange(mesh, dtypes: Sequence[T.DataType],
                       key_idx: Sequence[int], axis_name: str = "data"):
    """Back-compat wrapper over MeshExchange for non-string columns where
    the hash keys are table columns (older tests / dryrun helper)."""
    dts = list(dtypes)
    kset = list(key_idx)

    def run(datas: List[jax.Array], valids: List[jax.Array]):
        ex = MeshExchange(mesh, [dts[i] for i in kset], axis_name)
        live = jnp.ones(datas[0].shape[0], jnp.bool_)
        out_d, out_v, counts = ex.run(
            datas, valids, [datas[i] for i in kset],
            [valids[i] for i in kset], live)
        ndev = mesh.shape[axis_name]
        cap = datas[0].shape[0] // ndev
        out_live = []
        shard = ndev * cap
        liv = np.zeros(ndev * shard, dtype=bool)
        for d in range(ndev):
            liv[d * shard:d * shard + int(counts[d])] = True
        return out_d, out_v, jnp.asarray(liv)

    return run


def mesh_partial_then_merge(mesh, axis_name: str = "data"):
    """Partial-aggregate-per-shard + psum merge (the distributed two-phase
    GpuHashAggregate shape); used by the multichip dry run."""
    from jax.sharding import PartitionSpec as P_

    def build(local_fn):
        def wrapper(*args):
            partial_out = local_fn(*args)
            return jax.tree.map(lambda x: jax.lax.psum(x, axis_name),
                                partial_out)

        sm = _shard_map()
        return tpu_jit(sm(wrapper, mesh=mesh,
                          in_specs=P_(axis_name), out_specs=P_()))
    return build
