// LZ4 block-format codec (compress + safe decompress), implemented from the
// public block-format spec for the shuffle wire path.
//
// Reference parity (SURVEY.md §2.6): the reference compresses shuffle splits
// with nvcomp BatchedLZ4Compressor/BatchedZstdCompressor on the GPU
// (TableCompressionCodec.scala, NvcompLZ4CompressionCodec.scala). On TPU the
// shuffle wire stays host-side (serialized batches over files/sockets), so the
// codec is a host C++ hot path, matching how the reference keeps its codecs
// native. Format: raw LZ4 blocks — token(lit<<4|match-4), 255-extension
// lengths, 2-byte little-endian offsets, minimum match 4, last 5 bytes always
// literals, no match starting within the final 12 bytes.
//
// Exported C ABI (ctypes):
//   int64 lz4_compress_bound(int64 n)
//   int64 lz4_compress(src, n, dst, dst_cap)        -> compressed size or -1
//   int64 lz4_decompress(src, n, dst, dst_cap)      -> decompressed size or -1

#include <cstdint>
#include <cstring>

namespace {

constexpr int kMinMatch = 4;
constexpr int kLastLiterals = 5;   // spec: last 5 bytes are always literals
constexpr int kMfLimit = 12;       // spec: no match within last 12 bytes
constexpr int kHashLog = 16;
constexpr uint32_t kHashSize = 1u << kHashLog;
constexpr uint32_t kMaxOffset = 65535;

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - kHashLog);
}

}  // namespace

extern "C" {

int64_t lz4_compress_bound(int64_t n) {
  // worst case: incompressible data expands by 1 byte per 255 + token/lens
  return n + n / 255 + 16;
}

int64_t lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                     int64_t dst_cap) {
  // positions are stored as uint32 in the hash table; larger inputs would
  // wrap and could emit offsets into the wrong window — refuse them
  if (n < 0 || n >= (1ll << 32) || dst_cap < lz4_compress_bound(n)) return -1;
  uint8_t* op = dst;
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  const uint8_t* anchor = src;

  if (n >= kMfLimit) {
    const uint8_t* const mflimit = iend - kMfLimit;
    uint32_t table[kHashSize];
    std::memset(table, 0xff, sizeof(table));  // 0xffffffff = empty

    while (ip < mflimit) {
      // find a match via single-entry hash table
      uint32_t h = hash4(read32(ip));
      uint32_t cand = table[h];
      table[h] = static_cast<uint32_t>(ip - src);
      const uint8_t* match = src + cand;
      if (cand == 0xffffffffu || ip - match > kMaxOffset ||
          read32(match) != read32(ip)) {
        ++ip;
        continue;
      }
      // extend the match forward (stay clear of the final literals region)
      const uint8_t* const matchlimit = iend - kLastLiterals;
      const uint8_t* mp = match + kMinMatch;
      const uint8_t* cp = ip + kMinMatch;
      while (cp < matchlimit && *cp == *mp) {
        ++cp;
        ++mp;
      }
      int64_t match_len = cp - ip;
      int64_t lit_len = ip - anchor;

      // token + literal length
      uint8_t* token = op++;
      if (lit_len >= 15) {
        *token = 15 << 4;
        int64_t rem = lit_len - 15;
        while (rem >= 255) {
          *op++ = 255;
          rem -= 255;
        }
        *op++ = static_cast<uint8_t>(rem);
      } else {
        *token = static_cast<uint8_t>(lit_len << 4);
      }
      std::memcpy(op, anchor, static_cast<size_t>(lit_len));
      op += lit_len;

      // offset
      uint32_t offset = static_cast<uint32_t>(ip - match);
      *op++ = static_cast<uint8_t>(offset & 0xff);
      *op++ = static_cast<uint8_t>(offset >> 8);

      // match length (stored as len - 4)
      int64_t ml = match_len - kMinMatch;
      if (ml >= 15) {
        *token |= 15;
        ml -= 15;
        while (ml >= 255) {
          *op++ = 255;
          ml -= 255;
        }
        *op++ = static_cast<uint8_t>(ml);
      } else {
        *token |= static_cast<uint8_t>(ml);
      }

      ip += match_len;
      anchor = ip;
      if (ip < mflimit) {
        // re-prime the table at ip-2 to catch overlapping sequences
        table[hash4(read32(ip - 2))] = static_cast<uint32_t>(ip - 2 - src);
      }
    }
  }

  // trailing literals
  int64_t lit_len = iend - anchor;
  uint8_t* token = op++;
  if (lit_len >= 15) {
    *token = 15 << 4;
    int64_t rem = lit_len - 15;
    while (rem >= 255) {
      *op++ = 255;
      rem -= 255;
    }
    *op++ = static_cast<uint8_t>(rem);
  } else {
    *token = static_cast<uint8_t>(lit_len << 4);
  }
  std::memcpy(op, anchor, static_cast<size_t>(lit_len));
  op += lit_len;
  return op - dst;
}

int64_t lz4_decompress(const uint8_t* src, int64_t n, uint8_t* dst,
                       int64_t dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;

  if (n == 0) return dst_cap == 0 ? 0 : -1;

  for (;;) {
    if (ip >= iend) return -1;
    uint32_t token = *ip++;

    // literals
    int64_t lit_len = token >> 4;
    if (lit_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        lit_len += b;
      } while (b == 255);
    }
    if (lit_len > iend - ip || lit_len > oend - op) return -1;
    std::memcpy(op, ip, static_cast<size_t>(lit_len));
    ip += lit_len;
    op += lit_len;
    if (ip == iend) break;  // last sequence is literals-only

    // offset
    if (iend - ip < 2) return -1;
    uint32_t offset = ip[0] | (static_cast<uint32_t>(ip[1]) << 8);
    ip += 2;
    if (offset == 0 || offset > op - dst) return -1;

    // match length
    int64_t match_len = (token & 15) + kMinMatch;
    if ((token & 15) == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        match_len += b;
      } while (b == 255);
    }
    if (match_len > oend - op) return -1;
    const uint8_t* match = op - offset;
    if (offset >= static_cast<uint32_t>(match_len)) {
      std::memcpy(op, match, static_cast<size_t>(match_len));
      op += match_len;
    } else {
      // overlapping copy must run byte-by-byte (RLE-style back-reference)
      for (int64_t i = 0; i < match_len; ++i) op[i] = match[i];
      op += match_len;
    }
  }
  return op - dst;
}

}  // extern "C"
