"""Plan rewrite for the input_file_name() expression family.

Reference: InputFileBlockRule.scala — the reference walks the plan,
groups each chain [node with the first input_file_xxx expr ... FileScan)
and keeps the whole chain on one side so the expressions see the scan's
per-batch file context (issue #3333). This engine owns its logical
plans, so it can do better than constrain: the scan ATTACHES per-row
provenance columns and the expressions become bound references to them
— any plan shape above keeps working because the provenance is ordinary
column data from then on.

Rewrite contract (code-review r5 hardened):
- COPY-ON-WRITE: plan nodes are shared across DataFrames, so the rewrite
  never mutates an input node — every node it changes (the expression
  holder, intermediate chain nodes, the scan whose flag turns on) is a
  shallow copy with its own expression/children containers; execute()
  runs the returned plan while the original stays pristine for other
  queries sharing its nodes;
- a chain qualifies when the expression's node reaches a FileScanNode
  through Project/Filter/Limit-like single-child nodes only (no
  shuffle/aggregate/join boundary — Spark defines the expressions only
  within the scan's stage);
- intermediate Projects gain passthrough references BOTTOM-UP so each
  sees its child's already-widened schema;
- the hidden columns never escape the rewritten region: a
  schema-transparent expression holder (Filter/Limit/Sort) is wrapped in
  a dropping Project restoring its pre-rewrite schema, so expressions
  bound ABOVE it (join sides, projects) keep their ordinals;
- non-qualifying expressions are left in place and evaluate to Spark's
  "no file info" constants (ops/inputfile.py).
"""

from __future__ import annotations

import copy

from spark_rapids_tpu import plan as P
from spark_rapids_tpu.ops.expr import BoundReference
from spark_rapids_tpu.ops.inputfile import (
    FILE_INFO_COLS,
    contains_input_file_expr,
    substitute,
)

#: single-child, provenance-transparent nodes the chain may cross
_PASSTHROUGH = ("Filter", "Limit", "Sample", "Project")

#: plain expression-list attrs
_LIST_ATTRS = ("exprs", "grouping")


def _node_exprs(node):
    """Every expression a node holds, flat (for detection)."""
    out = []
    for attr in _LIST_ATTRS:
        out.extend(getattr(node, attr, ()) or ())
    cond = getattr(node, "condition", None)
    if cond is not None:
        out.append(cond)
    for _, fn in getattr(node, "agg_specs", ()) or ():
        out.append(fn)
    for o in getattr(node, "orders", ()) or ():
        out.append(o.expr)
    for _, w in getattr(node, "window_cols", ()) or ():
        out.append(w)
    return out


def _node_has_input_file(node) -> bool:
    return any(contains_input_file_expr(e) for e in _node_exprs(node))


def _shallow(node):
    """Copy a node so its expression/children containers are private."""
    n2 = copy.copy(node)
    for attr in ("exprs", "names", "grouping", "agg_specs", "orders",
                 "window_cols"):
        v = getattr(n2, attr, None)
        if isinstance(v, list):
            setattr(n2, attr, list(v))
    return n2


def _substitute_all(node, schema):
    """Substitute input_file_* in every expression container of a COPY."""
    for attr in _LIST_ATTRS:
        v = getattr(node, attr, None)
        if v:
            setattr(node, attr, [substitute(e, schema) for e in v])
    cond = getattr(node, "condition", None)
    if cond is not None:
        node.condition = substitute(cond, schema)
    specs = getattr(node, "agg_specs", None)
    if specs:
        node.agg_specs = [(n, substitute(f, schema)) for n, f in specs]
    orders = getattr(node, "orders", None)
    if orders:
        node.orders = [P.SortOrder(substitute(o.expr, schema), o.ascending,
                                   o.nulls_first) for o in orders]
    wcols = getattr(node, "window_cols", None)
    if wcols:
        node.window_cols = [(n, substitute(w, schema)) for n, w in wcols]


def _find_scan_chain(node):
    """[mid..., scan] when ``node`` reaches a FileScanNode through
    passthrough nodes only, else None."""
    from spark_rapids_tpu.io.common import FileScanNode
    chain = []
    cur = node
    while True:
        kids = list(getattr(cur, "children", ()))
        if len(kids) != 1:
            return None
        nxt = kids[0]
        if isinstance(nxt, FileScanNode):
            return chain + [nxt]
        if type(nxt).__name__ not in _PASSTHROUGH:
            return None
        chain.append(nxt)
        cur = nxt


def _drop_project(child, schema_keep):
    proj = P.Project.__new__(P.Project)
    proj.children = (child,)
    proj.names = [n for n, _ in schema_keep]
    child_schema = child.output_schema()
    idx = {n: i for i, (n, _) in enumerate(child_schema)}
    proj.exprs = [BoundReference(idx[n], dt, name_hint=n)
                  for n, dt in schema_keep]
    return proj


def rewrite_input_file_exprs(plan):
    """Copy-on-write rewrite; returns the plan to execute (the input plan
    and every node it shares with other queries stay untouched)."""

    def walk(node):
        kids = tuple(getattr(node, "children", ()))
        new_kids = tuple(walk(k) for k in kids)
        if any(nk is not k for nk, k in zip(new_kids, kids)):
            node = _shallow(node)
            node.children = new_kids
        if not _node_has_input_file(node):
            return node
        chain = _find_scan_chain(node)
        if chain is None:
            return node  # stays as the no-info constant
        before = node.output_schema()
        # clone the chain so the flag/passthroughs never touch shared nodes
        new_chain = [_shallow(c) for c in chain]
        for i in range(len(new_chain) - 1):
            new_chain[i].children = (new_chain[i + 1],)
        new_chain[-1].enable_file_info()
        # passthroughs BOTTOM-UP so each Project sees its child widened
        for mid in reversed(new_chain[:-1]):
            if type(mid).__name__ == "Project" and \
                    FILE_INFO_COLS[0] not in mid.names:
                cs = mid.children[0].output_schema()
                names = [n for n, _ in cs]
                for col in FILE_INFO_COLS:
                    i = names.index(col)
                    mid.exprs.append(BoundReference(i, cs[i][1],
                                                    name_hint=col))
                    mid.names.append(col)
        node = _shallow(node)
        node.children = (new_chain[0],)
        _substitute_all(node, node.children[0].output_schema())
        after = node.output_schema()
        if any(n in FILE_INFO_COLS for n, _ in after):
            # transparent holder (Filter/Limit/Sort): restore the
            # pre-rewrite schema so ordinals bound above stay valid
            return _drop_project(node, before)
        return node

    return walk(plan)
