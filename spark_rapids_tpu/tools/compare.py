"""A/B comparison of two event logs (two bench/scale runs).

Matches queries by tag (falling back to query index). A tag usually has
several runs per log (time_query's warm trials); wall times compare as
MEDIANS across runs (min reported alongside) and the per-operator
opTime/self-time diff uses each side's median-wall run — single-sample
comparisons would read run-to-run variance as regressions. Ops are
matched by their position-stable plan path (``op[childIndex]...``) so a
changed plan shape shows up as added/removed ops, not a garbled diff."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.tools.report import (
    _metric,
    load_events,
    query_label,
)


def _op_times(plan: dict) -> Dict[str, dict]:
    """Plan-path -> {op, opTimeS, selfTimeS, rows} for every metered
    node."""
    out: Dict[str, dict] = {}

    def walk(node: dict, path: str):
        own = float(_metric(node, "opTime", 0.0))
        child_total = sum(float(_metric(c, "opTime", 0.0))
                          for c in node.get("children", ()))
        if "opTime" in (node.get("metrics") or {}):
            out[path] = {
                "op": node.get("op"),
                "opTimeS": round(own, 6),
                "selfTimeS": round(max(own - child_total, 0.0), 6),
                "rows": int(_metric(node, "numOutputRows", 0)),
            }
        for i, c in enumerate(node.get("children", ())):
            walk(c, f"{path}.{c.get('op')}[{i}]")

    walk(plan, str(plan.get("op")))
    return out


def _index(records: List[dict]) -> Dict[str, List[dict]]:
    """label -> ALL records with that tag (a tagged query typically has
    several warm runs per report; collapsing to one sample would turn
    run-to-run variance into phantom regressions)."""
    out: Dict[str, List[dict]] = {}
    for r in records:
        if r.get("cacheHit"):
            continue  # replayed metrics + ~0 wall would skew medians
        out.setdefault(query_label(r), []).append(r)
    return out


def _median_record(runs: List[dict]) -> dict:
    """The run with the median wall time — the representative sample
    whose plan tree the per-op diff uses."""
    ordered = sorted(runs, key=lambda r: float(r.get("wallS", 0.0)))
    return ordered[len(ordered) // 2]


def _wall_stats(runs: List[dict]) -> Tuple[float, float]:
    walls = sorted(float(r.get("wallS", 0.0)) for r in runs)
    return walls[0], walls[len(walls) // 2]


def compare_query(a_runs: List[dict], b_runs: List[dict]) -> dict:
    a, b = _median_record(a_runs), _median_record(b_runs)
    min_a, wall_a = _wall_stats(a_runs)
    min_b, wall_b = _wall_stats(b_runs)
    ops_a = _op_times(a.get("plan") or {})
    ops_b = _op_times(b.get("plan") or {})
    op_diffs = []
    for path in sorted(set(ops_a) | set(ops_b)):
        ea, eb = ops_a.get(path), ops_b.get(path)
        if ea is None or eb is None:
            op_diffs.append({
                "path": path,
                "op": (ea or eb)["op"],
                "status": "removed" if eb is None else "added",
                "opTimeS": (ea or eb)["opTimeS"],
            })
            continue
        d = round(eb["opTimeS"] - ea["opTimeS"], 6)
        op_diffs.append({
            "path": path, "op": ea["op"], "status": "common",
            "aOpTimeS": ea["opTimeS"], "bOpTimeS": eb["opTimeS"],
            "deltaOpTimeS": d,
            "deltaSelfTimeS": round(eb["selfTimeS"] - ea["selfTimeS"], 6),
            "deltaRows": eb["rows"] - ea["rows"],
        })
    op_diffs.sort(key=lambda e: -abs(e.get("deltaOpTimeS",
                                           e.get("opTimeS", 0.0))))
    fb_a = {f["op"]: f["reasons"] for f in a.get("fallbacks") or []}
    fb_b = {f["op"]: f["reasons"] for f in b.get("fallbacks") or []}
    return {
        "query": query_label(a),
        # wall times are MEDIANS over the tag's runs (min alongside);
        # per-op detail comes from each side's median-wall run
        "aRuns": len(a_runs),
        "bRuns": len(b_runs),
        "aWallS": round(wall_a, 6),
        "bWallS": round(wall_b, 6),
        "aWallMinS": round(min_a, 6),
        "bWallMinS": round(min_b, 6),
        "deltaWallS": round(wall_b - wall_a, 6),
        "speedup": round(wall_a / wall_b, 4) if wall_b > 0 else None,
        "aDispatches": a.get("dispatches", 0),
        "bDispatches": b.get("dispatches", 0),
        # cold-vs-warm compile breakdown (schema v3): SUMS over the
        # tag's runs — a median would hide the one cold run per tag
        "aCompileMs": round(sum(float(r.get("compileMs", 0.0))
                                for r in a_runs), 3),
        "bCompileMs": round(sum(float(r.get("compileMs", 0.0))
                                for r in b_runs), 3),
        "aExecutableCacheHits": sum(
            1 for r in a_runs if r.get("executableCacheHit")),
        "bExecutableCacheHits": sum(
            1 for r in b_runs if r.get("executableCacheHit")),
        # survivability (schema v4): recovery events under each side's
        # runs — a perf regression explained by a device reinit mid-run
        # is a different conversation than a plan regression
        "aDeviceReinits": sum(int(r.get("deviceReinits", 0))
                              for r in a_runs),
        "bDeviceReinits": sum(int(r.get("deviceReinits", 0))
                              for r in b_runs),
        "aWorkerRestarts": sum(int(r.get("workerRestarts", 0))
                               for r in a_runs),
        "bWorkerRestarts": sum(int(r.get("workerRestarts", 0))
                               for r in b_runs),
        # mesh-native execution (schema v6): ICI payload per side — a
        # wall delta between an on-mesh and an off-mesh run shows up
        # here before anyone blames the plan
        "aIciBytes": sum(int(r.get("iciBytes", 0)) for r in a_runs),
        "bIciBytes": sum(int(r.get("iciBytes", 0)) for r in b_runs),
        # mesh fault domain (schema v7): recovery work the distributed
        # path paid per side — a wall regression explained by shard
        # retries or a mid-run degradation is not a plan regression
        "aShardRetries": sum(int(r.get("shardRetries", 0))
                             for r in a_runs),
        "bShardRetries": sum(int(r.get("shardRetries", 0))
                             for r in b_runs),
        "aMeshDegradations": sum(int(r.get("meshDegradations", 0))
                                 for r in a_runs),
        "bMeshDegradations": sum(int(r.get("meshDegradations", 0))
                                 for r in b_runs),
        "aGatherChecksFailed": sum(int(r.get("gatherChecksFailed", 0))
                                   for r in a_runs),
        "bGatherChecksFailed": sum(int(r.get("gatherChecksFailed", 0))
                                   for r in b_runs),
        # host fault domain (schema v8): per-side host losses / shard
        # re-lands / DCN crossings — a wall regression explained by a
        # mid-run host loss is not a plan regression
        "aHostsLost": sum(int(r.get("hostsLost", 0)) for r in a_runs),
        "bHostsLost": sum(int(r.get("hostsLost", 0)) for r in b_runs),
        "aHostRelands": sum(int(r.get("hostRelands", 0))
                            for r in a_runs),
        "bHostRelands": sum(int(r.get("hostRelands", 0))
                            for r in b_runs),
        "aDcnExchanges": sum(int(r.get("dcnExchanges", 0))
                             for r in a_runs),
        "bDcnExchanges": sum(int(r.get("dcnExchanges", 0))
                             for r in b_runs),
        # memory fault domain (schema v10): per-side spill/retry work —
        # a wall regression explained by out-of-core spilling under a
        # tighter budget is not a plan regression
        "aOomRetries": sum(int(r.get("oomRetries", 0)) for r in a_runs),
        "bOomRetries": sum(int(r.get("oomRetries", 0)) for r in b_runs),
        "aSplitRetries": sum(int(r.get("splitRetries", 0))
                             for r in a_runs),
        "bSplitRetries": sum(int(r.get("splitRetries", 0))
                             for r in b_runs),
        "aSpillBytes": sum(int(r.get("spillBytes", 0)) for r in a_runs),
        "bSpillBytes": sum(int(r.get("spillBytes", 0)) for r in b_runs),
        "aUnspills": sum(int(r.get("unspills", 0)) for r in a_runs),
        "bUnspills": sum(int(r.get("unspills", 0)) for r in b_runs),
        "ops": op_diffs,
        "newFallbacks": sorted(set(fb_b) - set(fb_a)),
        "resolvedFallbacks": sorted(set(fb_a) - set(fb_b)),
    }


def build_compare(path_a: str, path_b: str) -> dict:
    idx_a = _index(load_events(path_a))
    idx_b = _index(load_events(path_b))
    common = [k for k in idx_a if k in idx_b]
    queries = [compare_query(idx_a[k], idx_b[k]) for k in common]
    total_a = round(sum(q["aWallS"] for q in queries), 6)
    total_b = round(sum(q["bWallS"] for q in queries), 6)
    compile_a = round(sum(q["aCompileMs"] for q in queries), 3)
    compile_b = round(sum(q["bCompileMs"] for q in queries), 3)
    return {
        "a": path_a,
        "b": path_b,
        "matchedQueries": len(queries),
        "totalACompileMs": compile_a,
        "totalBCompileMs": compile_b,
        "deltaCompileMs": round(compile_b - compile_a, 3),
        "aDeviceReinits": sum(q["aDeviceReinits"] for q in queries),
        "bDeviceReinits": sum(q["bDeviceReinits"] for q in queries),
        "aWorkerRestarts": sum(q["aWorkerRestarts"] for q in queries),
        "bWorkerRestarts": sum(q["bWorkerRestarts"] for q in queries),
        "aIciBytes": sum(q["aIciBytes"] for q in queries),
        "bIciBytes": sum(q["bIciBytes"] for q in queries),
        "aShardRetries": sum(q["aShardRetries"] for q in queries),
        "bShardRetries": sum(q["bShardRetries"] for q in queries),
        "aMeshDegradations": sum(q["aMeshDegradations"] for q in queries),
        "bMeshDegradations": sum(q["bMeshDegradations"] for q in queries),
        "aGatherChecksFailed": sum(q["aGatherChecksFailed"]
                                   for q in queries),
        "bGatherChecksFailed": sum(q["bGatherChecksFailed"]
                                   for q in queries),
        "aOomRetries": sum(q["aOomRetries"] for q in queries),
        "bOomRetries": sum(q["bOomRetries"] for q in queries),
        "aSplitRetries": sum(q["aSplitRetries"] for q in queries),
        "bSplitRetries": sum(q["bSplitRetries"] for q in queries),
        "aSpillBytes": sum(q["aSpillBytes"] for q in queries),
        "bSpillBytes": sum(q["bSpillBytes"] for q in queries),
        "aUnspills": sum(q["aUnspills"] for q in queries),
        "bUnspills": sum(q["bUnspills"] for q in queries),
        "onlyInA": sorted(set(idx_a) - set(idx_b)),
        "onlyInB": sorted(set(idx_b) - set(idx_a)),
        "totalAWallS": total_a,
        "totalBWallS": total_b,
        "totalSpeedup": round(total_a / total_b, 4) if total_b > 0 else None,
        "queries": queries,
    }


def render_compare(cmp: dict, top_n: int = 5) -> str:
    lines: List[str] = []
    lines.append(f"A: {cmp['a']}")
    lines.append(f"B: {cmp['b']}")
    lines.append(f"Matched queries: {cmp['matchedQueries']}   total "
                 f"{cmp['totalAWallS']:.4f}s -> {cmp['totalBWallS']:.4f}s"
                 + (f"   speedup {cmp['totalSpeedup']}x"
                    if cmp["totalSpeedup"] else ""))
    for side, key in (("only in A", "onlyInA"), ("only in B", "onlyInB")):
        if cmp[key]:
            lines.append(f"  {side}: {', '.join(cmp[key])}")
    lines.append(f"Compile: {cmp['totalACompileMs']:.1f}ms -> "
                 f"{cmp['totalBCompileMs']:.1f}ms "
                 f"({cmp['deltaCompileMs']:+.1f}ms)")
    if cmp["aIciBytes"] or cmp["bIciBytes"]:
        lines.append(f"Mesh: ICI bytes {cmp['aIciBytes']} -> "
                     f"{cmp['bIciBytes']}")
    if (cmp.get("aShardRetries") or cmp.get("bShardRetries")
            or cmp.get("aMeshDegradations")
            or cmp.get("bMeshDegradations")
            or cmp.get("aGatherChecksFailed")
            or cmp.get("bGatherChecksFailed")):
        lines.append(
            f"Mesh resilience: shard retries {cmp['aShardRetries']} -> "
            f"{cmp['bShardRetries']} | degradations "
            f"{cmp['aMeshDegradations']} -> {cmp['bMeshDegradations']} | "
            f"gather checks failed {cmp['aGatherChecksFailed']} -> "
            f"{cmp['bGatherChecksFailed']}")
    if (cmp.get("aOomRetries") or cmp.get("bOomRetries")
            or cmp.get("aSpillBytes") or cmp.get("bSpillBytes")
            or cmp.get("aSplitRetries") or cmp.get("bSplitRetries")):
        lines.append(
            f"Memory: oom retries {cmp['aOomRetries']} -> "
            f"{cmp['bOomRetries']} | split retries "
            f"{cmp['aSplitRetries']} -> {cmp['bSplitRetries']} | "
            f"spilled {cmp['aSpillBytes']} -> {cmp['bSpillBytes']} "
            f"bytes | unspills {cmp['aUnspills']} -> {cmp['bUnspills']}")
    if (cmp["aDeviceReinits"] or cmp["bDeviceReinits"]
            or cmp["aWorkerRestarts"] or cmp["bWorkerRestarts"]):
        lines.append(
            f"Survivability: device reinits "
            f"{cmp['aDeviceReinits']} -> {cmp['bDeviceReinits']} | "
            f"worker restarts {cmp['aWorkerRestarts']} -> "
            f"{cmp['bWorkerRestarts']}")
    for q in cmp["queries"]:
        arrow = f"{q['aWallS']:.4f}s -> {q['bWallS']:.4f}s"
        sp = f"  ({q['speedup']}x)" if q.get("speedup") else ""
        runs = (f"  [median of {q['aRuns']}/{q['bRuns']} runs]"
                if max(q["aRuns"], q["bRuns"]) > 1 else "")
        lines.append(f"  {q['query']:16s} {arrow}{sp}  dispatches "
                     f"{q['aDispatches']} -> {q['bDispatches']}{runs}")
        for e in q["ops"][:top_n]:
            if e["status"] != "common":
                lines.append(f"      {e['status']:7s} {e['path']} "
                             f"({e['opTimeS']:.4f}s)")
            elif e["deltaOpTimeS"]:
                lines.append(
                    f"      {e['deltaOpTimeS']:+9.4f}s {e['path']} "
                    f"(self {e['deltaSelfTimeS']:+.4f}s, rows "
                    f"{e['deltaRows']:+d})")
        if q["newFallbacks"]:
            lines.append(f"      NEW fallbacks: {', '.join(q['newFallbacks'])}")
        if q["resolvedFallbacks"]:
            lines.append("      resolved fallbacks: "
                         + ", ".join(q["resolvedFallbacks"]))
    return "\n".join(lines)
