"""PySpark-style function namespace (the user-facing expression builders)."""

from __future__ import annotations

from spark_rapids_tpu.ops.expr import col, lit, Expression  # noqa: F401
from spark_rapids_tpu.ops import aggregates as _agg
from spark_rapids_tpu.ops import conditional as _cond
from spark_rapids_tpu.ops import math as _math
from spark_rapids_tpu.ops import predicates as _pred


def _e(x) -> Expression:
    return x if isinstance(x, Expression) else col(x) if isinstance(x, str) else lit(x)


# aggregates
def sum(e):  # noqa: A001
    return _agg.Sum(_e(e))


def min(e):  # noqa: A001
    return _agg.Min(_e(e))


def max(e):  # noqa: A001
    return _agg.Max(_e(e))


def count(e="*"):
    # NOTE: col("x") == "*" builds an EqualTo EXPRESSION (truthy), so the
    # star check must be type-guarded or count(col) silently becomes
    # count(*) with different null semantics
    if (isinstance(e, str) and e == "*") or (isinstance(e, int) and e == 1):
        return _agg.Count()
    return _agg.Count(_e(e))


def avg(e):
    return _agg.Average(_e(e))


mean = avg


def first(e, ignore_nulls=False):
    return _agg.First(_e(e), ignore_nulls)


def last(e, ignore_nulls=False):
    return _agg.Last(_e(e), ignore_nulls)


def collect_list(e):
    return _agg.CollectList(_e(e))


def collect_set(e):
    return _agg.CollectSet(_e(e))


def percentile(e, p: float):
    return _agg.Percentile(_e(e), p)


def stddev(e):
    return _agg.StddevSamp(_e(e))


def stddev_pop(e):
    return _agg.StddevPop(_e(e))


def variance(e):
    return _agg.VarianceSamp(_e(e))


def var_pop(e):
    return _agg.VariancePop(_e(e))


# conditionals
def when(cond, value):
    return WhenBuilder().when(cond, value)


class WhenBuilder:
    def __init__(self):
        self._branches = []

    def when(self, cond, value):
        self._branches.extend([_e(cond), _e(value)])
        return self

    def otherwise(self, value):
        return _cond.CaseWhen(*self._branches, _e(value))

    def end(self):
        return _cond.CaseWhen(*self._branches)


def coalesce(*exprs):
    return _cond.Coalesce(*[_e(e) for e in exprs])


def greatest(*exprs):
    return _cond.Greatest(*[_e(e) for e in exprs])


def least(*exprs):
    return _cond.Least(*[_e(e) for e in exprs])


def nanvl(a, b):
    return _cond.NaNvl(_e(a), _e(b))


def if_(cond, a, b):
    return _cond.If(_e(cond), _e(a), _e(b))


def isnull(e):
    return _pred.IsNull(_e(e))


def isnan(e):
    return _pred.IsNaN(_e(e))


def is_in(e, *items):
    return _pred.In(_e(e), [_e(i) for i in items])


# math
def sqrt(e):
    return _math.Sqrt(_e(e))


def exp(e):
    return _math.Exp(_e(e))


def log(e):
    return _math.Log(_e(e))


def log10(e):
    return _math.Log10(_e(e))


def log2(e):
    return _math.Log2(_e(e))


def pow(a, b):  # noqa: A001
    return _math.Pow(_e(a), _e(b))


def abs(e):  # noqa: A001
    from spark_rapids_tpu.ops.arithmetic import Abs
    return Abs(_e(e))


def ceil(e):
    return _math.Ceil(_e(e))


def floor(e):
    return _math.Floor(_e(e))


def round(e, scale=0):  # noqa: A001
    return _math.Round(_e(e), lit(scale))


def bround(e, scale=0):
    return _math.BRound(_e(e), lit(scale))


def signum(e):
    return _math.Signum(_e(e))


def shiftleft(e, n):
    return _math.ShiftLeft(_e(e), _e(n))


def shiftright(e, n):
    return _math.ShiftRight(_e(e), _e(n))


# window functions: thin delegates to the single implementations in
# ops/window.py (reference: window/ package exprs)
def input_file_name():
    """Name of the file feeding the current row ('' when no file scan is
    in scope — Spark semantics)."""
    from spark_rapids_tpu.ops.inputfile import InputFileName
    return InputFileName()


def input_file_block_start():
    from spark_rapids_tpu.ops.inputfile import InputFileBlockStart
    return InputFileBlockStart()


def input_file_block_length():
    from spark_rapids_tpu.ops.inputfile import InputFileBlockLength
    return InputFileBlockLength()


def row_number():
    from spark_rapids_tpu.ops import window as _w
    return _w.row_number()


def rank():
    from spark_rapids_tpu.ops import window as _w
    return _w.rank()


def dense_rank():
    from spark_rapids_tpu.ops import window as _w
    return _w.dense_rank()


def percent_rank():
    from spark_rapids_tpu.ops.window import PercentRank
    return PercentRank()


def nth_value(e, n: int):
    from spark_rapids_tpu.ops.window import NthValue
    return NthValue(_e(e), n)


def lag(e, offset: int = 1, default=None):
    from spark_rapids_tpu.ops import window as _w
    return _w.lag(_e(e), offset, default)


def lead(e, offset: int = 1, default=None):
    from spark_rapids_tpu.ops import window as _w
    return _w.lead(_e(e), offset, default)


# string functions (ops/strings.py)
def _str_fns():
    from spark_rapids_tpu.ops import strings as s
    return s


def upper(e):
    return _str_fns().Upper(_e(e))


def lower(e):
    return _str_fns().Lower(_e(e))


def length(e):
    return _str_fns().Length(_e(e))


def bit_length(e):
    return _str_fns().BitLength(_e(e))


def octet_length(e):
    return _str_fns().OctetLength(_e(e))


def ascii(e):  # noqa: A001
    return _str_fns().Ascii(_e(e))


def reverse(e):
    return _str_fns().Reverse(_e(e))


def initcap(e):
    return _str_fns().InitCap(_e(e))


def trim(e):
    return _str_fns().StringTrim(_e(e))


def ltrim(e):
    return _str_fns().StringTrimLeft(_e(e))


def rtrim(e):
    return _str_fns().StringTrimRight(_e(e))


def substring(e, pos, length):  # noqa: A002
    return _str_fns().Substring(_e(e), lit(pos), lit(length))


def repeat(e, n):
    return _str_fns().StringRepeat(_e(e), lit(n))


def replace(e, search, replacement=""):
    return _str_fns().StringReplace(_e(e), lit(search), lit(replacement))


def lpad(e, length, pad=" "):  # noqa: A002
    return _str_fns().StringLPad(_e(e), lit(length), lit(pad))


def rpad(e, length, pad=" "):  # noqa: A002
    return _str_fns().StringRPad(_e(e), lit(length), lit(pad))


def substring_index(e, delim, count):
    return _str_fns().SubstringIndex(_e(e), lit(delim), lit(count))


def translate(e, matching, replace):  # noqa: A002
    return _str_fns().StringTranslate(_e(e), lit(matching), lit(replace))


def concat(*exprs):
    return _str_fns().Concat(*[_e(x) for x in exprs])


def contains(e, sub):
    return _str_fns().Contains(_e(e), lit(sub))


def startswith(e, prefix):
    return _str_fns().StartsWith(_e(e), lit(prefix))


def endswith(e, suffix):
    return _str_fns().EndsWith(_e(e), lit(suffix))


def like(e, pattern):
    return _str_fns().Like(_e(e), lit(pattern))


def rlike(e, pattern):
    return _str_fns().RLike(_e(e), lit(pattern))


def instr(e, sub):
    return _str_fns().StringInstr(_e(e), lit(sub))


def locate(sub, e, pos=1):
    return _str_fns().StringLocate(lit(sub), _e(e), lit(pos))


def regexp_replace(e, pattern, replacement):
    return _str_fns().RegExpReplace(_e(e), lit(pattern), lit(replacement))


def regexp_extract(e, pattern, idx=1):
    return _str_fns().RegExpExtract(_e(e), lit(pattern), lit(idx))


# datetime functions (ops/datetime.py)
def _dt_fns():
    from spark_rapids_tpu.ops import datetime as d
    return d


def year(e):
    return _dt_fns().Year(_e(e))


def month(e):
    return _dt_fns().Month(_e(e))


def dayofmonth(e):
    return _dt_fns().DayOfMonth(_e(e))


def dayofweek(e):
    return _dt_fns().DayOfWeek(_e(e))


def weekday(e):
    return _dt_fns().WeekDay(_e(e))


def dayofyear(e):
    return _dt_fns().DayOfYear(_e(e))


def quarter(e):
    return _dt_fns().Quarter(_e(e))


def last_day(e):
    return _dt_fns().LastDay(_e(e))


def date_add(e, n):
    return _dt_fns().DateAdd(_e(e), _e(n))


def date_sub(e, n):
    return _dt_fns().DateSub(_e(e), _e(n))


def datediff(end, start):
    return _dt_fns().DateDiff(_e(end), _e(start))


def add_months(e, n):
    return _dt_fns().AddMonths(_e(e), _e(n))


def hour(e):
    return _dt_fns().Hour(_e(e))


def minute(e):
    return _dt_fns().Minute(_e(e))


def second(e):
    return _dt_fns().Second(_e(e))


def to_unix_timestamp(e):
    return _dt_fns().UnixTimestampFromTs(_e(e))


def timestamp_seconds(e):
    return _dt_fns().SecondsToTimestamp(_e(e))


def timestamp_millis(e):
    return _dt_fns().MillisToTimestamp(_e(e))


def timestamp_micros(e):
    return _dt_fns().MicrosToTimestamp(_e(e))


def to_date(e):
    return _dt_fns().TsToDate(_e(e))


# hash functions (ops/hashfns.py)
def hash(*exprs):  # noqa: A001
    from spark_rapids_tpu.ops.hashfns import Murmur3Hash
    return Murmur3Hash(*[_e(x) for x in exprs])


def xxhash64(*exprs):
    from spark_rapids_tpu.ops.hashfns import XxHash64
    return XxHash64(*[_e(x) for x in exprs])


# -- collections ------------------------------------------------------------

def size(e):
    from spark_rapids_tpu.ops.collections import Size
    return Size(_e(e))


def array(*exprs):
    from spark_rapids_tpu.ops.collections import CreateArray
    return CreateArray(*[_e(x) for x in exprs])


def array_contains(e, value):
    from spark_rapids_tpu.ops.collections import ArrayContains
    return ArrayContains(_e(e), _e(value))


def array_min(e):
    from spark_rapids_tpu.ops.collections import ArrayMin
    return ArrayMin(_e(e))


def array_max(e):
    from spark_rapids_tpu.ops.collections import ArrayMax
    return ArrayMax(_e(e))


def sort_array(e, asc: bool = True):
    from spark_rapids_tpu.ops.collections import SortArray
    return SortArray(_e(e), lit(asc))


def get_item(e, index):
    from spark_rapids_tpu.ops.collections import GetArrayItem
    return GetArrayItem(_e(e), _e(index))


def explode(e):
    from spark_rapids_tpu.ops.collections import Explode
    return Explode(_e(e))


def explode_outer(e):
    from spark_rapids_tpu.ops.collections import ExplodeOuter
    return ExplodeOuter(_e(e))


def posexplode(e):
    from spark_rapids_tpu.ops.collections import PosExplode
    return PosExplode(_e(e))


def posexplode_outer(e):
    from spark_rapids_tpu.ops.collections import PosExplodeOuter
    return PosExplodeOuter(_e(e))


# -- UDF compiler -----------------------------------------------------------

def udf(fn, return_type=None):
    """Compile a Python lambda/function into an engine expression builder
    (udf-compiler analog); see spark_rapids_tpu.udf."""
    from spark_rapids_tpu.udf import udf as _udf
    return _udf(fn, return_type)


# -- misc -------------------------------------------------------------------

def monotonically_increasing_id():
    from spark_rapids_tpu.ops.misc import MonotonicallyIncreasingID
    return MonotonicallyIncreasingID()


def spark_partition_id():
    from spark_rapids_tpu.ops.misc import SparkPartitionID
    return SparkPartitionID()


def rand(seed: int = 0):
    from spark_rapids_tpu.ops.misc import Rand
    return Rand(seed)


def md5(e):
    from spark_rapids_tpu.ops.misc import Md5
    return Md5(_e(e))


def concat_ws(sep, *exprs):
    from spark_rapids_tpu.ops.misc import ConcatWs
    # the separator is a VALUE (PySpark signature), not a column name
    sep_expr = sep if isinstance(sep, Expression) else lit(sep)
    return ConcatWs(sep_expr, *[_e(x) for x in exprs])


def from_utc_timestamp(e, tz):
    from spark_rapids_tpu.ops.misc import FromUTCTimestamp
    return FromUTCTimestamp(_e(e), _e(tz))


def to_utc_timestamp(e, tz):
    from spark_rapids_tpu.ops.misc import ToUTCTimestamp
    return ToUTCTimestamp(_e(e), _e(tz))


def pandas_udf(return_type, function_type: str = "scalar"):
    """Pandas UDF factory (reference: execution/python/ pandas UDF execs).
    @F.pandas_udf("double") for scalar (Series -> Series per batch);
    @F.pandas_udf("double", "grouped_agg") for group aggregates
    (Series -> scalar per group, used in group_by().agg())."""
    from spark_rapids_tpu.plan.pandas_udf import pandas_udf as _pu
    return _pu(return_type, function_type)


# -- nested types: structs, maps, higher-order functions ---------------------
# (reference: complexTypeCreator.scala, higherOrderFunctions.scala)

def _lambda(fn, n_vars: int):
    """Build a LambdaFunction from a Python callable: F.transform(c,
    lambda x: x + 1) — the callable runs ONCE at plan time with symbolic
    variables (the Spark Connect / PySpark column-lambda idiom)."""
    from spark_rapids_tpu.ops.nested import LambdaFunction, NamedLambdaVariable
    if isinstance(fn, LambdaFunction):
        return fn
    import inspect
    names = list(inspect.signature(fn).parameters)[:n_vars] or \
        [f"x{i}" for i in range(n_vars)]
    body = fn(*[NamedLambdaVariable(n) for n in names])
    return LambdaFunction(_e(body), names)


def struct(*exprs, names=None):
    from spark_rapids_tpu.ops.expr import output_name
    from spark_rapids_tpu.ops.nested import CreateNamedStruct
    es = [_e(x) for x in exprs]
    if names is None:
        names = [output_name(e, f"col{i}") for i, e in enumerate(es)]
    return CreateNamedStruct(names, es)


def named_struct(*name_expr_pairs):
    from spark_rapids_tpu.ops.nested import CreateNamedStruct
    names = [name_expr_pairs[i] for i in range(0, len(name_expr_pairs), 2)]
    es = [_e(name_expr_pairs[i]) for i in range(1, len(name_expr_pairs), 2)]
    return CreateNamedStruct(names, es)


def get_field(e, name: str):
    from spark_rapids_tpu.ops.nested import GetStructField
    return GetStructField(_e(e), name)


def create_map(*exprs):
    from spark_rapids_tpu.ops.nested import CreateMap
    return CreateMap(*[_e(x) for x in exprs])


def map_keys(e):
    from spark_rapids_tpu.ops.nested import MapKeys
    return MapKeys(_e(e))


def map_values(e):
    from spark_rapids_tpu.ops.nested import MapValues
    return MapValues(_e(e))


def map_entries(e):
    from spark_rapids_tpu.ops.nested import MapEntries
    return MapEntries(_e(e))


def map_concat(*exprs):
    from spark_rapids_tpu.ops.nested import MapConcat
    return MapConcat(*[_e(x) for x in exprs])


def get_map_value(m, key):
    from spark_rapids_tpu.ops.nested import GetMapValue
    return GetMapValue(_e(m), _e(key))


def transform(arr, fn):
    from spark_rapids_tpu.ops.nested import ArrayTransform
    lam = _lambda(fn, 2 if _lambda_arity(fn) >= 2 else 1)
    return ArrayTransform(_e(arr), lam)


def filter_array(arr, fn):
    from spark_rapids_tpu.ops.nested import ArrayFilter
    return ArrayFilter(_e(arr), _lambda(fn, _lambda_arity(fn)))


def exists(arr, fn):
    from spark_rapids_tpu.ops.nested import ArrayExists
    return ArrayExists(_e(arr), _lambda(fn, 1))


def forall(arr, fn):
    from spark_rapids_tpu.ops.nested import ArrayForAll
    return ArrayForAll(_e(arr), _lambda(fn, 1))


def map_filter(m, fn):
    from spark_rapids_tpu.ops.nested import MapFilter
    return MapFilter(_e(m), _lambda(fn, 2))


def transform_keys(m, fn):
    from spark_rapids_tpu.ops.nested import TransformKeys
    return TransformKeys(_e(m), _lambda(fn, 2))


def transform_values(m, fn):
    from spark_rapids_tpu.ops.nested import TransformValues
    return TransformValues(_e(m), _lambda(fn, 2))


def arrays_zip(*exprs):
    from spark_rapids_tpu.ops.nested import ArraysZip
    return ArraysZip(*[_e(x) for x in exprs])


def _lambda_arity(fn) -> int:
    import builtins
    from spark_rapids_tpu.ops.nested import LambdaFunction
    if isinstance(fn, LambdaFunction):
        return len(fn.var_names)
    import inspect
    return builtins.max(len(inspect.signature(fn).parameters), 1)


def build_bloom_filter(df, column, num_bits=None, num_hashes=None):
    """bloom_filter_agg analog: aggregate a DataFrame column into a
    device-resident BloomFilter handle (ops/bloom.py)."""
    from spark_rapids_tpu.ops import bloom as B
    kw = {}
    if num_bits is not None:
        kw["num_bits"] = num_bits
    if num_hashes is not None:
        kw["num_hashes"] = num_hashes
    return B.build_bloom_filter(df, column, **kw)


def might_contain(bloom, e):
    from spark_rapids_tpu.ops.bloom import BloomFilterMightContain
    return BloomFilterMightContain(bloom, _e(e))


def from_json(e, schema):
    """from_json(col, schema) -> struct (GpuJsonToStructs analog)."""
    from spark_rapids_tpu.ops.json_structs import JsonToStructs
    return JsonToStructs(_e(e), schema)


def to_json(e):
    from spark_rapids_tpu.ops.json_structs import StructsToJson
    return StructsToJson(_e(e))


def sequence(start, stop, step=None):
    from spark_rapids_tpu.ops.collections import Sequence
    args = [_e(start), _e(stop)]
    if step is not None:
        args.append(_e(step))
    return Sequence(*args)


def approx_percentile(e, percentage, accuracy: int = 10000):
    """approx_percentile — served EXACTLY by the device sort-based
    percentile (any answer within Spark's accuracy contract; exact
    satisfies every accuracy)."""
    from spark_rapids_tpu.ops.aggregates import Percentile
    return Percentile(_e(e), percentage)


approxPercentile = approx_percentile


# -- SQL front end hooks ------------------------------------------------------

def expr(sql_text: str) -> Expression:
    """Parse one SQL expression into an engine Expression (PySpark
    F.expr analog): F.expr("l_extendedprice * (1.0 - l_discount)").
    Column references resolve at plan-bind time like col()."""
    from spark_rapids_tpu.sql.analyzer import Analyzer, Scope
    from spark_rapids_tpu.sql.parser import parse_expression

    node = parse_expression(sql_text)
    analyzer = Analyzer(None, sql_text)

    class _OpenScope(Scope):
        """Unbound scope: any identifier resolves to an
        AttributeReference; binding happens when the expression lands
        in a plan node (exactly like col())."""

        def __init__(self):
            pass

        @property
        def columns(self):
            return _AnyContains()

        aliases: dict = {}
        visible: list = []

    class _AnyContains(list):
        def __contains__(self, item):
            return True

    return analyzer.lower_expr(node, _OpenScope())


#: process-wide SQL-callable function registrations (session-scoped ones
#: live in SessionCatalog.register_function)
_SQL_FUNCTIONS = {}


def register_sql_function(name: str, builder) -> None:
    """Make ``builder(*arg_exprs) -> Expression`` callable from SQL text
    under ``name`` in every session — e.g. a compiled Python UDF:
    ``register_sql_function("plus_one", F.udf(lambda x: x + 1))``."""
    _SQL_FUNCTIONS[name.lower()] = builder


def unregister_sql_function(name: str) -> None:
    _SQL_FUNCTIONS.pop(name.lower(), None)


def registered_sql_function(name: str):
    return _SQL_FUNCTIONS.get(name.lower())
