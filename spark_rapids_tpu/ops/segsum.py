"""Fast f64 segmented sums for the TPU.

On TPU, float64 storage is native but every compute op is emulated (XLA
rewrites f64 into (f32, f32) pair arithmetic), and the scatter-add inside an
emulated-f64 ``segment_sum`` dominates aggregation time (~5x the cost of the
f32 one). ``segment_sum_f64`` computes the same reduction through an EXACT
hi/lo f32 decomposition — on TPU every f64 value is exactly ``f32(x) +
f32(x - f32(x))`` because the storage itself is an f32 pair:

  1. per-(segment, block) partial sums of ``hi`` and ``lo`` run as plain f32
     scatter-adds (a block of 1024 rows bounds f32 accumulation error);
  2. the (num_segments * num_blocks) partials combine in emulated f64 —
     tiny compared to the input.

Accuracy: the decomposition is exact; the only rounding is f32 accumulation
within one block. That error scales with the segment's ABSOLUTE mass
(sum |x|), so the kernel self-checks at runtime: alongside the split sums it
accumulates per-segment |hi| mass and reroutes the whole batch to the exact
emulated path (``lax.cond``) whenever the estimated error could exceed 1e-6
relative — which catches both huge magnitudes (|x| > 1e34 would overflow an
f32 block partial) and catastrophic cancellation (mass >> |sum|). On
well-conditioned data (TPC-style positive measures) the observed error is
~1e-9 relative (tests/test_agg_fastpath.py).

This is the same class of trade the reference makes for float aggregation:
GPU float sums differ from CPU Spark in ULPs by reduction order and are
gated by ``spark.rapids.sql.variableFloatAgg.enabled``
(reference: aggregate.scala GpuSum, RapidsConf.scala). The exact emulated
path stays available via ``spark.rapids.tpu.sum.splitF64=false``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# the limb split/recombine recipes live in ops/limbs.py (single source
# of truth for kernels/ and the HLO paths); re-exported here because
# half the engine historically imported them from this module
from spark_rapids_tpu.ops.limbs import (  # noqa: F401
    combine_f64,
    combine_i64,
    split_f64_hi_lo,
    split_i64_hi_lo,
)

#: rows per f32 partial-sum block — bounds f32 accumulation error
BLOCK = 1024

#: batches with |x| above this could overflow an f32 block partial
SPLIT_MAX_ABS = 1e34

#: error estimate per unit of absolute segment mass (eps_f32 with an 8x
#: safety margin over the random-walk expectation)
ERR_PER_MASS = 4.8e-7

#: the split result is accepted when est. error <= RTOL * |sum| + ATOL
RTOL = 1e-6
ATOL = 1e-12

#: don't let (num_segments * num_blocks) partials outgrow the input
MAX_PARTIALS = 1 << 22


def trace_key():
    """Tuning values that change the shape of a traced kernel — any
    trace cache keyed on split-sum behavior must include this (a cached
    trace would silently keep a superseded conf value otherwise)."""
    return (BLOCK, MAX_PARTIALS, MATMUL_MAX_SEGMENTS, float(SPLIT_MAX_ABS))


def resolve_split_mode(conf) -> bool:
    """Resolve spark.rapids.tpu.sum.splitF64 ('auto' = split on non-CPU
    backends, where f64 is emulated; CPU f64 is native and exact)."""
    from spark_rapids_tpu.conf import SPLIT_F64_SUM
    mode = str(conf.get_entry(SPLIT_F64_SUM)).strip().lower()
    if mode in ("true", "1", "on"):
        return True
    if mode in ("false", "0", "off"):
        return False
    return jax.default_backend() != "cpu"


#: one-hot MXU matmul partials when num_segments is at most this (the
#: materialized one-hot costs capacity*num_segments*4 bytes of HBM traffic)
MATMUL_MAX_SEGMENTS = 32


def batched_segment_sum_f64(cols, gid, num_segments: int, capacity: int,
                            use_split: bool, counts=None):
    """Segmented sums of several f64 columns in ONE device pass.

    ``cols``: list of (capacity,) f64 arrays, invalid slots zeroed. Returns
    (num_segments, len(cols)) f64. Small segment counts reduce hi/lo/|hi|
    f32 streams with one blocked one-hot einsum on the MXU; medium counts
    use blocked 2-D scatter partials; large counts (beyond MAX_PARTIALS)
    take _batched_unblocked_split's per-stream 1-D scatters with the
    count-scaled guard. All paths share the exact-fallback guard (the
    whole batch reroutes if ANY column is risky); ``counts`` optionally
    feeds the unblocked guard a precomputed row-count bound."""
    m = len(cols)
    if m == 0:
        return jnp.zeros((num_segments, 0), dtype=jnp.float64)
    block = min(BLOCK, capacity)
    nb = max(capacity // block, 1)
    if not use_split or cols[0].dtype != jnp.float64 or nb * block != capacity:
        return jax.ops.segment_sum(jnp.stack(cols, axis=1), gid,
                                   num_segments=num_segments)
    if nb * num_segments > MAX_PARTIALS:
        # large segment counts (int-domain fast-path group-bys): per-block
        # partials would outgrow the input, but the emulated-f64 scatter
        # fallback is the single most expensive op on TPU — run the
        # UNBLOCKED split instead (f32 scatters + count-scaled guard)
        return _batched_unblocked_split(cols, gid, num_segments,
                                        counts=counts)

    his, los, abss = [], [], []
    for c in cols:
        hi, lo = split_f64_hi_lo(c)
        his.append(hi)
        los.append(lo)
        abss.append(jnp.abs(hi))
    x = jnp.stack(his + los + abss, axis=1)  # (capacity, 3m)

    if num_segments <= MATMUL_MAX_SEGMENTS:
        def hlo_parts():
            oh = jax.nn.one_hot(gid.reshape(nb, block), num_segments,
                                dtype=jnp.float32)
            return jnp.einsum('nbc,nbg->ngc', x.reshape(nb, block, 3 * m),
                              oh, precision='highest')

        def kern_parts():
            from spark_rapids_tpu.kernels import segreduce as kseg
            return kseg.onehot_partials(x, gid, num_segments, nb, block)

        from spark_rapids_tpu import kernels
        parts = kernels.dispatch("segreduce", kern_parts, hlo_parts)
    else:
        blk = jnp.arange(capacity, dtype=jnp.int32) // block
        ids = blk * num_segments + gid
        parts = jax.ops.segment_sum(
            x, ids, num_segments=nb * num_segments
        ).reshape(nb, num_segments, 3 * m)
    p64 = parts.astype(jnp.float64).sum(axis=0)  # (num_segments, 3m)
    shi, slo, mass = p64[:, :m], p64[:, m:2 * m], p64[:, 2 * m:]
    split_sum = shi + slo

    err_est = mass * ERR_PER_MASS
    risky = err_est > (jnp.abs(split_sum) * RTOL + ATOL)
    has_big = jnp.any(mass * 0 != 0) | jnp.any(
        jnp.max(jnp.abs(x[:, :m]), axis=0) > SPLIT_MAX_ABS)
    bad = jnp.any(risky) | has_big

    def exact(_):
        return jax.ops.segment_sum(jnp.stack(cols, axis=1), gid,
                                   num_segments=num_segments)

    return jax.lax.cond(bad, exact, lambda _: split_sum,
                        jnp.zeros((), dtype=jnp.int32))


def _batched_unblocked_split(cols, gid, num_segments: int, counts=None):
    """Unblocked split for SEVERAL f64 columns at a large segment count.

    Every 1-D scatter pass over the input costs ~100ms at 4M rows on TPU
    (XLA scatter with duplicate indices serializes), so the pass count IS
    the cost model here:
      - hi and lo streams: one scatter each (unavoidable — the sums);
      - |hi| mass for the error guard: SKIPPED when every value is
        globally non-negative (then mass == hi sum exactly — the
        TPC-measure common case), else one scatter per column via
        lax.cond;
      - per-segment row count for the guard's scale term: callers that
        already scattered nonnull counts (the aggregate kernels) pass
        them via ``counts`` ((num_segments,) or (num_segments, m) i32,
        an UPPER bound on contributing rows) and the scatter is skipped.
    Per-stream 1-D scatters, never a (capacity, 3m) 2-D scatter: the TPU
    lane width is 128 and a 2-D scatter pads the tiny minor dim to it."""
    m = len(cols)
    his, los = [], []
    for c in cols:
        hi, lo = split_f64_hi_lo(c)
        his.append(hi)
        los.append(lo)
    parts = jnp.stack(
        [jax.ops.segment_sum(st, gid, num_segments=num_segments)
         for st in his + los], axis=1)
    if counts is None:
        any_nz = jnp.zeros(cols[0].shape, dtype=jnp.bool_)
        for c in cols:
            any_nz = any_nz | (c != 0.0)
        cnt2 = jax.ops.segment_sum(any_nz.astype(jnp.int32), gid,
                                   num_segments=num_segments)[:, None]
    else:
        cnt2 = counts if counts.ndim == 2 else counts[:, None]
    p64 = parts.astype(jnp.float64)
    shi, slo = p64[:, :m], p64[:, m:2 * m]
    split_sum = shi + slo

    all_nonneg = jnp.ones((), dtype=jnp.bool_)
    for hi in his:
        all_nonneg = all_nonneg & jnp.all(hi >= 0)

    def mass_from_hi(_):
        return shi

    def mass_scatter(_):
        return jnp.stack(
            [jax.ops.segment_sum(jnp.abs(hi), gid,
                                 num_segments=num_segments)
             for hi in his], axis=1).astype(jnp.float64)

    mass = jax.lax.cond(all_nonneg, mass_from_hi, mass_scatter,
                        jnp.zeros((), dtype=jnp.int32))

    scale = jnp.sqrt(jnp.maximum(cnt2.astype(jnp.float64) / BLOCK, 1.0))
    err_est = ERR_PER_MASS * scale * mass
    risky = err_est > (jnp.abs(split_sum) * RTOL + ATOL)
    has_big = jnp.zeros((), dtype=jnp.bool_)
    for c in cols:
        has_big = has_big | jnp.any(jnp.abs(c) > SPLIT_MAX_ABS)
    has_nonfinite = ~jnp.all(jnp.isfinite(mass))
    bad = jnp.any(risky) | has_big | has_nonfinite

    def exact(_):
        return jax.ops.segment_sum(jnp.stack(cols, axis=1), gid,
                                   num_segments=num_segments)

    return jax.lax.cond(bad, exact, lambda _: split_sum,
                        jnp.zeros((), dtype=jnp.int32))


def segment_minmax_64(is_min: bool, sd, sv, gid, num_segments: int):
    """Exact 64-bit segment min/max through NATIVE 32-bit scatters.

    The emulated-64-bit compare-select inside a scatter is the most
    expensive segment op on TPU (~100ms at 1M rows x 32k segments, vs
    sub-ms for a 32-bit scatter). Both 64-bit dtypes order
    lexicographically by (high limb, low limb):

      f64: x == hi + lo with hi = f32(x) (monotone rounding) and the
           residual lo carrying the tie-break — reduce hi with a native
           f32 scatter, then reduce lo over rows whose hi equals the
           winner; mhi + mlo reconstructs the winning f64 EXACTLY.
      i64: (top 32 bits signed, low 32 bits unsigned).

    Float NaN follows Spark's ordering (NaN greatest): max yields NaN if
    any NaN; min ignores NaN unless the segment is all-NaN. Returns
    per-segment values with EMPTY segments undefined (callers mask by
    their own has_any). reference: GpuMin/GpuMax in aggregate.scala run
    cudf device reductions; this is the TPU-shaped equivalent."""
    red = jax.ops.segment_min if is_min else jax.ops.segment_max

    def _limb_minmax(hi, lo, use, hi_ident, lo_ident):
        """(per-segment hi winner, lo tiebreak) — the Pallas fused
        two-pass kernel when enabled, else the two HLO segment
        reductions; bit-identical either way (min/max reductions are
        exactly associative)."""
        def hlo():
            mhi = red(jnp.where(use, hi, hi_ident), gid,
                      num_segments=num_segments)
            cand = use & (hi == mhi[gid])
            mlo = red(jnp.where(cand, lo, lo_ident), gid,
                      num_segments=num_segments)
            return mhi, mlo

        def kern():
            from spark_rapids_tpu.kernels import segreduce as kseg
            return kseg.fused_minmax(is_min, hi, lo, use, gid,
                                     num_segments, hi_ident, lo_ident)

        from spark_rapids_tpu import kernels
        return kernels.dispatch("segreduce", kern, hlo)

    if sd.dtype == jnp.float64:
        isnan = jnp.isnan(sd) & sv
        use = sv & ~isnan
        hi, lo = split_f64_hi_lo(sd)

        def fast(_):
            ident = jnp.float32(jnp.inf if is_min else -jnp.inf)
            mhi, mlo = _limb_minmax(hi, lo, use, ident, ident)
            return combine_f64(mhi, mlo)

        def exact(_):
            ident = jnp.float64(jnp.inf if is_min else -jnp.inf)
            return red(jnp.where(use, sd, ident), gid,
                       num_segments=num_segments)

        # On TPU f64 IS an (f32, f32) pair so the split is exact for every
        # representable value; on CPU backends with split forced on, values
        # outside f32 range (overflow to inf) or below it (subnormal /
        # underflow-to-zero) don't round-trip — reroute to the emulated-64
        # reduction whenever hi+lo fails to reconstruct any used input.
        recon = hi.astype(jnp.float64) + lo.astype(jnp.float64)
        lossy = jnp.any(use & ~jnp.isnan(sd) & (recon != sd))
        out = jax.lax.cond(lossy, exact, fast,
                           jnp.zeros((), dtype=jnp.int32))
        any_nan = jax.ops.segment_max(isnan.astype(jnp.int32), gid,
                                      num_segments=num_segments) > 0
        if is_min:
            n_use = jax.ops.segment_sum(use.astype(jnp.int32), gid,
                                        num_segments=num_segments)
            return jnp.where(any_nan & (n_use == 0), jnp.float64(jnp.nan), out)
        return jnp.where(any_nan, jnp.float64(jnp.nan), out)
    hi, lo = split_i64_hi_lo(sd)
    info = jnp.iinfo(jnp.int32)
    hi_ident = jnp.int32(info.max if is_min else info.min)
    lo_ident = jnp.uint32(0xFFFFFFFF if is_min else 0)
    mhi, mlo = _limb_minmax(hi, lo, sv, hi_ident, lo_ident)
    return combine_i64(mhi, mlo)


def _unblocked_split_segment_sum(v, gid, num_segments: int):
    """Split path for LARGE segment counts (sorted-path aggregates run
    with num_segments == capacity, where per-block partials would outgrow
    the input): the m=1 case of _batched_unblocked_split — ONE guard
    implementation serves both (code-review r5: three hand-rolled copies
    of the error model drifted apart)."""
    return _batched_unblocked_split([v], gid, num_segments)[:, 0]


def segment_sum_f64(v, gid, num_segments: int, capacity: int,
                    use_split: bool, counts=None):
    """segment_sum for f64 ``v`` (invalid slots must already be zeroed).

    ``gid`` must be int32 in [0, num_segments). Non-f64 dtypes and
    disabled split configurations take the plain jax.ops.segment_sum
    path; oversized configurations (num_segments*blocks would outgrow
    the input) take the guarded UNBLOCKED split path. ``counts``: an
    optional caller-scattered per-segment row-count upper bound — the
    unblocked guard reuses it instead of scattering its own."""
    if v.dtype != jnp.float64 or not use_split:
        return jax.ops.segment_sum(v, gid, num_segments=num_segments)
    block = min(BLOCK, capacity)
    nb = max(capacity // block, 1)
    if nb * block != capacity or nb * num_segments > MAX_PARTIALS:
        if counts is not None:
            return _batched_unblocked_split([v], gid, num_segments,
                                            counts=counts)[:, 0]
        return _unblocked_split_segment_sum(v, gid, num_segments)

    hi, lo = split_f64_hi_lo(v)
    blk = jnp.arange(capacity, dtype=jnp.int32) // block
    ids = blk * num_segments + gid
    phi = jax.ops.segment_sum(hi, ids, num_segments=nb * num_segments)
    plo = jax.ops.segment_sum(lo, ids, num_segments=nb * num_segments)
    pabs = jax.ops.segment_sum(jnp.abs(hi), ids, num_segments=nb * num_segments)
    parts = phi.astype(jnp.float64) + plo.astype(jnp.float64)
    split_sum = parts.reshape(nb, num_segments).sum(axis=0)
    mass = pabs.reshape(nb, num_segments).sum(axis=0).astype(jnp.float64)

    err_est = mass * ERR_PER_MASS
    risky = err_est > (jnp.abs(split_sum) * RTOL + ATOL)
    has_big = jnp.any(jnp.abs(v) > SPLIT_MAX_ABS)
    has_nonfinite = ~jnp.all(jnp.isfinite(mass))
    bad = jnp.any(risky) | has_big | has_nonfinite

    def exact(x):
        return jax.ops.segment_sum(x, gid, num_segments=num_segments)

    return jax.lax.cond(bad, exact, lambda x: split_sum, v)
