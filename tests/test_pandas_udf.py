"""Pandas/Arrow Python UDF exec tests (reference: udf_test.py +
execution/python/ execs — SURVEY.md §2.3/§3.5)."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import functions as F
from spark_rapids_tpu.errors import ColumnarProcessingError
from spark_rapids_tpu.ops.expr import col
from spark_rapids_tpu import types as T


def _df(s, n=600, batches=3, seed=0):
    rng = np.random.default_rng(seed)
    return s.create_dataframe(
        {"k": rng.integers(0, 8, n).astype(np.int64),
         "v": rng.standard_normal(n),
         "w": rng.integers(-50, 50, n).astype(np.int64)},
        num_batches=batches)


# -- map_in_pandas -----------------------------------------------------------

def test_map_in_pandas(session, cpu_session):
    def fn(pdfs):
        for pdf in pdfs:
            out = pdf[pdf.v > 0][["k", "v"]].copy()
            out["v2"] = out.v * 2
            yield out

    def q(s):
        return _df(s).map_in_pandas(
            fn, [("k", T.LONG), ("v", T.DOUBLE), ("v2", T.DOUBLE)])

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    assert len(got) > 0


def test_map_in_pandas_runs_on_tpu(session):
    df = _df(session).map_in_pandas(
        lambda it: (pdf[["k"]] for pdf in it), [("k", T.LONG)])
    plan = df.explain()
    assert "TpuMapInPandasExec" in plan or "MapInPandas" in plan
    assert df.count() == 600


def test_map_in_pandas_schema_mismatch_raises(session):
    df = _df(session).map_in_pandas(
        lambda it: (pdf[["k"]] for pdf in it),
        [("missing", T.STRING)])
    with pytest.raises(ColumnarProcessingError, match="declared schema"):
        df.collect()


# -- apply_in_pandas (FlatMapGroupsInPandas) --------------------------------

def test_apply_in_pandas(session, cpu_session):
    def center(pdf):
        out = pdf.copy()
        out["v"] = out.v - out.v.mean()
        return out[["k", "v"]]

    def q(s):
        return (_df(s).group_by("k")
                .apply_in_pandas(center, [("k", T.LONG), ("v", T.DOUBLE)]))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want) == 600
    for g, w in zip(got, want):
        assert g[0] == w[0]
        assert abs(g[1] - w[1]) <= 1e-9 * max(1.0, abs(w[1]))


def test_apply_in_pandas_shrinking_groups(session):
    # fn returning one row per group (top-1 by v)
    def top1(pdf):
        return pdf.nlargest(1, "v")[["k", "v"]]

    df = (_df(session).group_by("k")
          .apply_in_pandas(top1, [("k", T.LONG), ("v", T.DOUBLE)]))
    rows = df.collect()
    assert len(rows) == 8  # one per key


# -- grouped-agg pandas UDFs (AggregateInPandas) ----------------------------

def test_aggregate_in_pandas(session, cpu_session):
    @F.pandas_udf("double", "grouped_agg")
    def mean_udf(v: pd.Series) -> float:
        return float(v.mean())

    @F.pandas_udf("long", "grouped_agg")
    def span_udf(w: pd.Series) -> int:
        return int(w.max() - w.min())

    def q(s):
        return (_df(s).group_by("k")
                .agg(mean_udf("v").alias("m"), span_udf("w").alias("s")))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want) == 8
    for g, w in zip(got, want):
        assert g[0] == w[0] and g[2] == w[2]
        assert abs(g[1] - w[1]) <= 1e-9 * max(1.0, abs(w[1]))


def test_mixing_pandas_and_builtin_aggs_rejected(session):
    @F.pandas_udf("double", "grouped_agg")
    def m(v):
        return float(v.mean())

    with pytest.raises(ValueError, match="cannot mix"):
        _df(session).group_by("k").agg(m("v"), F.sum("v").alias("s"))


# -- scalar pandas UDFs (ArrowEvalPython) -----------------------------------

def test_scalar_pandas_udf_in_select(session, cpu_session):
    @F.pandas_udf("double")
    def plus_one(v: pd.Series) -> pd.Series:
        return v + 1.0

    @F.pandas_udf("string")
    def fmt(k: pd.Series, w: pd.Series) -> pd.Series:
        return k.astype(str) + ":" + w.astype(str)

    def q(s):
        return _df(s).select("k", plus_one("v").alias("v1"),
                             fmt("k", "w").alias("t"))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert got == want
    assert isinstance(got[0][2], str) and ":" in got[0][2]


def test_scalar_udf_over_expression_args(session, cpu_session):
    @F.pandas_udf("double")
    def square(x: pd.Series) -> pd.Series:
        return x * x

    def q(s):
        return _df(s).select(square(col("v") + col("w")).alias("sq"))

    got = sorted(q(session).collect())
    want = sorted(q(cpu_session).collect())
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert abs(g[0] - w[0]) <= 1e-9 * max(1.0, abs(w[0]))


def test_nested_scalar_udf_rejected(session):
    @F.pandas_udf("double")
    def p1(v):
        return v + 1

    with pytest.raises(ColumnarProcessingError, match="top-level"):
        _df(session).select((p1("v") + col("w")).alias("x"))


def test_wrong_length_result_raises(session):
    @F.pandas_udf("double")
    def bad(v: pd.Series) -> pd.Series:
        return v.head(3)

    df = _df(session).select(bad("v").alias("x"))
    with pytest.raises(ColumnarProcessingError, match="rows"):
        df.collect()


# -- worker semaphore --------------------------------------------------------

def test_python_worker_semaphore_bounds_concurrency(session):
    import threading
    from spark_rapids_tpu.session import TpuSession

    live = [0]
    peak = [0]
    lock = threading.Lock()

    def probe(pdf):
        with lock:
            live[0] += 1
            peak[0] = max(peak[0], live[0])
        import time
        time.sleep(0.02)
        with lock:
            live[0] -= 1
        return pdf[["k", "v"]]

    s = TpuSession({"spark.rapids.python.concurrentPythonWorkers": "1"})
    df = (_df(s).group_by("k")
          .apply_in_pandas(probe, [("k", T.LONG), ("v", T.DOUBLE)]))
    assert df.count() == 600
    assert peak[0] == 1


def test_nested_udf_execs_do_not_deadlock():
    """map_in_pandas over a child scalar-UDF exec with ONE worker permit:
    the semaphore must be thread-reentrant (review fix)."""
    from spark_rapids_tpu.session import TpuSession
    s = TpuSession({"spark.rapids.python.concurrentPythonWorkers": "1"})

    @F.pandas_udf("double")
    def plus_one(v):
        return v + 1.0

    inner = _df(s).select("k", plus_one("v").alias("v1"))
    out = inner.map_in_pandas(
        lambda it: (pdf[pdf.v1 > 1.0] for pdf in it),
        [("k", T.LONG), ("v1", T.DOUBLE)])
    assert out.count() > 0
